"""Multi-process tests without a cluster (SURVEY.md §4.3) + fault injection.

These spawn real OS processes through launch.py: the actual
``jax.distributed.initialize`` rendezvous, per-host data sharding, and the
launcher's failure propagation — the behaviors fake-device tests can't see.
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(nprocs, script_args, timeout=240, cpu_devices=2,
                log_dir=None):
    cmd = [sys.executable, os.path.join(REPO, "launch.py"),
           "--nprocs", str(nprocs), "--cpu-devices", str(cpu_devices)]
    if log_dir is not None:
        cmd += ["--log-dir", str(log_dir)]
    cmd += ["--", *script_args]
    return subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                          cwd=REPO)


@pytest.mark.slow
def test_two_process_training_world(tmp_path):
    """2 procs x 2 fake devices -> one 4-device world; trains + checkpoints."""
    res = _run_launch(2, [
        "main.py", "--distributed", "--config", "resnet18_cifar10",
        "--epochs", "1", "--steps-per-epoch", "2", "--batch-size", "16",
        "--workers", "0", "--log-every", "2",
        "--checkpoint-dir", str(tmp_path / "ck"),
    ])
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "epoch 0" in res.stdout
    # the world really formed: per-chip rate must be rate/4, printed as such
    committed = [d for d in os.listdir(tmp_path / "ck") if d.startswith("step_")]
    assert committed, "no checkpoint written by the 2-process run"


def test_failed_rank_tears_down_launcher(tmp_path):
    """A dead rank must fail the whole job quickly (no hang) — the
    torchrun-style contract; recovery is restart-from-checkpoint."""
    script = tmp_path / "failing_rank.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        if os.environ.get("PROCESS_ID") == "1":
            sys.exit(3)
        time.sleep(120)
    """))
    t0 = time.time()
    res = _run_launch(2, [str(script)], timeout=60)
    assert res.returncode == 3
    assert time.time() - t0 < 30, "launcher did not tear down promptly"


@pytest.mark.slow
def test_restart_and_resume_after_rank_kill(tmp_path):
    """The full TPU recovery story (SURVEY.md §5): a host process dies
    mid-epoch -> the gang-scheduled job fails fast -> a relaunch with
    ``--resume auto`` continues from the last committed checkpoint with no
    epoch replay."""
    common = [
        "main.py", "--distributed", "--config", "resnet18_cifar10",
        "--model", "resnet_micro",
        "--epochs", "2", "--steps-per-epoch", "3", "--batch-size", "16",
        "--workers", "0", "--log-every", "1",
        "--checkpoint-dir", str(tmp_path / "ck"),
    ]
    # Rank 1 is hard-killed (os._exit) at global step 4 — one step into
    # epoch 1, after epoch 0's checkpoint (step 3) committed.
    t0 = time.time()
    res = _run_launch(2, common + ["--fault-inject", "1:4"], timeout=240)
    assert res.returncode == 57, res.stdout[-2000:] + res.stderr[-2000:]
    assert time.time() - t0 < 180, "job did not fail fast after rank death"
    committed = [d for d in os.listdir(tmp_path / "ck")
                 if d.startswith("step_")
                 and os.path.exists(tmp_path / "ck" / d / "COMMIT")]
    assert committed == ["step_00000003"], committed

    # Relaunch with --resume auto: must continue at epoch 1 (no replay of
    # epoch 0) and finish the remaining steps.
    res2 = _run_launch(2, common + ["--resume", "auto"], timeout=240)
    assert res2.returncode == 0, res2.stdout[-2000:] + res2.stderr[-2000:]
    assert "resumed from step 3 (epoch 1)" in res2.stdout
    assert "epoch 0 step" not in res2.stdout  # no epoch replay
    assert "epoch 1 step 3/3" in res2.stdout
    steps = [d for d in os.listdir(tmp_path / "ck") if d.startswith("step_")
             and os.path.exists(tmp_path / "ck" / d / "COMMIT")]
    assert "step_00000006" in steps  # epoch 1's checkpoint committed


def test_mid_epoch_kill_resume_is_sample_exact(tmp_path):
    """Step-granular checkpointing (VERDICT r4 missing #1): a process
    hard-killed MID-epoch resumes from a --checkpoint-every-steps save at
    the exact next unseen sample — no replay, no skip. Verified two ways:
    the optimizer-step count in the checkpoint id vs the consumed-index
    log of the resumed run, against the sampler's deterministic epoch
    permutation."""
    import json

    from pytorch_distributed_training_example_tpu.data.loader import (
        INDEX_LOG_ENV)
    from pytorch_distributed_training_example_tpu.data.sampler import (
        ShardedSampler)

    spe, bs = 5, 16
    common = [
        sys.executable, "main.py", "--platform", "cpu", "--fake-devices", "2",
        "--config", "resnet18_cifar10", "--model", "resnet_micro",
        "--epochs", "2", "--steps-per-epoch", str(spe),
        "--batch-size", str(bs), "--workers", "0", "--log-every", "1",
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--checkpoint-every-steps", "2",
    ]
    # Hard-kill (os._exit, no flushes) at global step 9 = one step before
    # the end of epoch 1; mid-epoch saves landed after epoch-1 steps 1 and 3.
    res = subprocess.run(common + ["--fault-inject", "0:9"],
                         capture_output=True, text=True, timeout=300,
                         cwd=REPO, env={**os.environ,
                                        INDEX_LOG_ENV: str(tmp_path / "i1")})
    assert res.returncode == 57, res.stdout[-2000:] + res.stderr[-2000:]
    committed = sorted(int(d.split("_")[1]) for d in os.listdir(tmp_path / "ck")
                       if d.startswith("step_")
                       and os.path.exists(tmp_path / "ck" / d / "COMMIT"))
    latest = committed[-1]
    assert latest > spe, f"no committed mid-epoch save in epoch 1: {committed}"
    applied_in_epoch1 = latest - spe  # optimizer steps of epoch 1 in the ckpt

    res2 = subprocess.run(common + ["--resume", "auto"],
                          capture_output=True, text=True, timeout=300,
                          cwd=REPO, env={**os.environ,
                                         INDEX_LOG_ENV: str(tmp_path / "i2")})
    assert res2.returncode == 0, res2.stdout[-2000:] + res2.stderr[-2000:]
    assert (f"resumed from step {latest} (epoch 1, step offset "
            f"{applied_in_epoch1})") in res2.stdout
    assert "epoch 0 step" not in res2.stdout  # no epoch replay

    # The resumed run's epoch-1 consumption must start EXACTLY at the first
    # unseen batch (no replay) and proceed in order through the epoch cap
    # (no skip). The loader legitimately overfetches a few batches past the
    # steps-per-epoch cap (prefetch pipeline), so assert on the trained
    # window [applied, spe) plus the contiguity of everything logged.
    rows = [json.loads(l) for l in (tmp_path / "i2").read_text().splitlines()
            if json.loads(l)["epoch"] == 1]
    batches = [r["batch"] for r in rows]
    assert batches[0] == applied_in_epoch1, "replayed or skipped a batch"
    assert batches == list(range(applied_in_epoch1,
                                 applied_in_epoch1 + len(batches)))
    assert batches[:spe - applied_in_epoch1] == list(
        range(applied_in_epoch1, spe))
    # synthetic CIFAR train fallback = 51200 examples (datasets.py)
    sampler = ShardedSampler(51200, 1, 0, shuffle=True, seed=0, drop_last=True)
    sampler.set_epoch(1)
    want = sampler.local_indices()[applied_in_epoch1 * bs: spe * bs]
    got = [i for r in rows[:spe - applied_in_epoch1] for i in r["indices"]]
    assert got == [int(x) for x in want]
    # run completed: epoch-1 boundary checkpoint (2 epochs x 5 steps)
    assert os.path.exists(tmp_path / "ck" / "step_00000010" / "COMMIT")


def test_launcher_requires_command():
    res = subprocess.run([sys.executable, os.path.join(REPO, "launch.py"),
                          "--nprocs", "2"], capture_output=True, text=True,
                         cwd=REPO, timeout=60)
    assert res.returncode != 0
    assert "no command" in res.stderr


@pytest.mark.slow
def test_disjoint_checkpoint_dir_fails_fast(tmp_path):
    """VERDICT r2 weak #5: the commit rendezvous assumes a shared
    filesystem. Pointing each rank at a different directory must raise at
    Checkpointer init (fail-fast), not time out 600s per save later."""
    script = tmp_path / "disjoint_ck.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        import jax
        jax.config.update("jax_platforms", "cpu")  # sitecustomize pins axon
        from pytorch_distributed_training_example_tpu.core import checkpoint, distributed
        distributed.init_process_group()
        rank_dir = os.path.join(%r, f"rank_{jax.process_index()}")
        os.makedirs(rank_dir, exist_ok=True)
        try:
            checkpoint.Checkpointer(rank_dir)
        except RuntimeError as e:
            assert "SHARED filesystem" in str(e), e
            print("FS_VALIDATION_RAISED", flush=True)
            sys.exit(7)
        print("no error", flush=True)
    """) % (REPO, str(tmp_path)))
    res = _run_launch(2, [str(script)], timeout=120)
    assert res.returncode == 7, res.stdout[-2000:] + res.stderr[-2000:]
    assert "FS_VALIDATION_RAISED" in res.stdout


@pytest.mark.slow
def test_shared_checkpoint_dir_passes_validation(tmp_path):
    """Same probe, shared directory: validation is silent and save works."""
    script = tmp_path / "shared_ck.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        import jax
        jax.config.update("jax_platforms", "cpu")  # sitecustomize pins axon
        from pytorch_distributed_training_example_tpu.core import checkpoint, distributed
        distributed.init_process_group()
        ck = checkpoint.Checkpointer(os.path.join(%r, "shared"))
        print("FS_VALIDATION_OK", flush=True)
    """) % (REPO, str(tmp_path)))
    res = _run_launch(2, [str(script)], timeout=120)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "FS_VALIDATION_OK" in res.stdout


@pytest.mark.slow
def test_multihost_eval_agreement(tmp_path):
    """VERDICT r2 weak #6: evaluate() divides global metric sums on the host
    per-process; every host must arrive at the SAME numbers (eval batches
    are globally sharded, eval_stats returns global sums). Non-main ranks
    suppress logging, so each rank prints its result directly."""
    script = tmp_path / "eval_agree.py"
    script.write_text(textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        import jax
        jax.config.update("jax_platforms", "cpu")  # sitecustomize pins axon
        from pytorch_distributed_training_example_tpu.core import distributed
        from pytorch_distributed_training_example_tpu.core.trainer import Trainer
        from pytorch_distributed_training_example_tpu.utils.config import from_preset
        distributed.init_process_group()
        cfg = from_preset("resnet18_cifar10", global_batch_size=16,
                          steps_per_epoch=2, epochs=1, workers=0,
                          checkpoint_dir=%r)
        t = Trainer(cfg)
        avg = t.evaluate(0)
        print("EVALRES", jax.process_index(),
              sorted((k, round(v, 6)) for k, v in avg.items()), flush=True)
    """) % (REPO, str(tmp_path / "ck")))
    # Rank-1 log routed under tmp_path (r3 advisor: a shared hardcoded
    # /tmp path can carry stale EVALRES lines across runs).
    res = _run_launch(2, [str(script)], timeout=240, log_dir=tmp_path)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    lines = [l for l in (res.stdout + res.stderr).splitlines()
             if l.startswith("EVALRES")]
    with open(tmp_path / "launch_rank1.log") as fh:
        lines += [l for l in fh.read().splitlines() if l.startswith("EVALRES")]
    results = {l.split()[1]: l.split(" ", 2)[2] for l in lines}
    assert set(results) == {"0", "1"}, lines
    assert results["0"] == results["1"], (
        f"hosts disagree on eval metrics: {results}")
