"""Disaggregation + router: KV handoff identity, chunked prefill, affinity
placement, drain/kill drills.

Two claims carry the PR: (1) splitting prefill and decode into separate
engines with an explicit KV-page handoff changes WHERE tokens are computed
but never WHICH tokens come out; (2) the router can lose a replica
mid-stream (graceful drain or hard kill) and still complete every request
with the same greedy tokens, because re-routed requests recompute from
their prompts deterministically.
"""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.serve import (
    engine as engine_lib, router as router_lib)
from pytorch_distributed_training_example_tpu.serve.router import (
    PrefixAffinityRouter, chunk_keys)


def _tiny(seq_len=128):
    bundle = registry.create_model("llama_tiny", seq_len=seq_len,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    module = bundle.module
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                         train=False)["params"]
    return module, params


def _reference_greedy(module, params, prompt, steps):
    toks = list(prompt)
    out = []
    for _ in range(steps):
        logits = module.apply({"params": params},
                              jnp.asarray([toks], jnp.int32), train=False)
        out.append(int(jnp.argmax(logits[0, len(toks) - 1])))
        toks.append(out[-1])
    return out


def _engine(module, params, spec, **kw):
    kw.setdefault("decode_buckets", (1, 2))
    kw.setdefault("prompt_buckets", (16, 32))
    kw.setdefault("max_model_len", 48)
    return engine_lib.ContinuousBatchingEngine(module, params, spec, **kw)


# ---------------------------------------------------------------------------
# chunk_keys: process-stable hashing
# ---------------------------------------------------------------------------


def test_chunk_keys_stable_and_prefix_consistent():
    a = chunk_keys([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
    assert len(a) == 2  # one key per FULL chunk; the 1-token tail has none
    assert a == chunk_keys([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
    # Shared prefix -> shared key chain prefix; divergence changes the rest.
    b = chunk_keys([1, 2, 3, 4, 9, 9, 9, 9], 4)
    assert b[0] == a[0] and b[1] != a[1]
    assert chunk_keys([1, 2, 3], 4) == []


# ---------------------------------------------------------------------------
# disaggregation: handoff identity + compile flatness, chunked prefill
# ---------------------------------------------------------------------------


def test_disaggregated_tokens_match_unified(devices):
    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    pair = engine_lib.DisaggregatedServe(
        _engine(module, params, spec, role="prefill"),
        _engine(module, params, spec, role="decode"))
    n = pair.warmup()
    rng = np.random.default_rng(31)
    reqs = [engine_lib.Request(request_id=f"d{i}",
                               prompt=rng.integers(1, 512, plen).tolist(),
                               max_new_tokens=8)
            for i, plen in enumerate([5, 8, 17, 24])]
    for r in reqs:
        pair.submit(r)
    done = {r.request_id: r for r in pair.run()}
    assert len(done) == 4
    assert pair.stats["handoffs_out"] == 4 == pair.stats["handoffs_in"]
    for r in reqs:
        ref = _reference_greedy(module, params, r.prompt, r.max_new_tokens)
        assert done[r.request_id].generated == ref, r.request_id
    # Both roles stayed inside their warmed executables.
    assert pair.stats["compiles"] == n


def test_chunked_prefill_matches_whole_prompt(devices):
    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    eng = _engine(module, params, spec, prefill_chunk=16)
    n = eng.warmup()
    rng = np.random.default_rng(33)
    # Longer than one chunk -> prefilled in 16-token windows through the
    # history-attention program; shorter -> single window, plain prefill.
    reqs = [engine_lib.Request(request_id=f"w{i}",
                               prompt=rng.integers(1, 512, plen).tolist(),
                               max_new_tokens=6)
            for i, plen in enumerate([31, 12, 17])]
    for r in reqs:
        eng.submit(r)
    done = {r.request_id: r for r in eng.run()}
    assert len(done) == 3
    for r in reqs:
        ref = _reference_greedy(module, params, r.prompt, r.max_new_tokens)
        assert done[r.request_id].generated == ref, r.request_id
    assert eng.stats["compiles"] == n


def test_disaggregate_rejects_mismatched_pair(devices):
    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    with pytest.raises(ValueError):
        engine_lib.DisaggregatedServe(
            _engine(module, params, spec, role="decode"),
            _engine(module, params, spec, role="prefill"))
    decode_only = _engine(module, params, spec, role="decode")
    with pytest.raises(ValueError):
        decode_only.submit(engine_lib.Request("x", [1, 2, 3], 4))


# ---------------------------------------------------------------------------
# router: affinity placement, least-loaded fallback, drain/kill drills
# ---------------------------------------------------------------------------


def _router(module, params, n=2, policy="affinity", **ekw):
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    replicas = {f"replica{i}": _engine(module, params, spec,
                                       prefix_cache=True, **ekw)
                for i in range(n)}
    for rep in replicas.values():
        rep.warmup()
    return PrefixAffinityRouter(replicas, page_size=8, policy=policy)


def _shared_prefix_reqs(rng, shared, count, tail=6, new=5, tag="r"):
    return [engine_lib.Request(
        request_id=f"{tag}{i}",
        prompt=list(shared) + rng.integers(1, 512, tail).tolist(),
        max_new_tokens=new) for i in range(count)]


def test_affinity_routes_shared_prefix_to_one_replica(devices):
    module, params = _tiny()
    router = _router(module, params)
    rng = np.random.default_rng(41)
    groups = [rng.integers(1, 512, 16).tolist() for _ in range(2)]
    placements = {0: set(), 1: set()}
    for i in range(6):
        g = i % 2
        r = _shared_prefix_reqs(rng, groups[g], 1, tag=f"g{g}_{i}")[0]
        router.submit(r)
        placements[g].add(router._placed[r.request_id])
        router.run()
    # Every request in a group landed on the group's first-placement owner.
    assert len(placements[0]) == 1 and len(placements[1]) == 1
    assert router.stats["affinity_hits"] >= 4
    fleet = router.fleet_stats()
    assert sum(rep["completed"] for rep in fleet["replicas"].values()) == 6
    # The shared prefixes actually hit the owning replica's cache.
    hits = sum(rep["stats"]["cached_tokens"]
               for rep in fleet["replicas"].values())
    assert hits > 0


def test_least_loaded_policy_spreads_saturation(devices):
    module, params = _tiny()
    router = _router(module, params, policy="least_loaded")
    rng = np.random.default_rng(43)
    shared = rng.integers(1, 512, 16).tolist()
    for r in _shared_prefix_reqs(rng, shared, 4):
        router.submit(r)
    done = router.run()
    assert len(done) == 4
    fleet = router.fleet_stats()
    loads = [rep["completed"] for rep in fleet["replicas"].values()]
    assert loads == [2, 2]  # identical prompts would pile up under affinity
    assert router.stats["affinity_hits"] == 0


def test_drain_finishes_actives_and_reroutes_waiting(devices):
    module, params = _tiny()
    router = _router(module, params)
    rng = np.random.default_rng(47)
    shared = rng.integers(1, 512, 16).tolist()
    reqs = _shared_prefix_reqs(rng, shared, 5, new=8)
    ref = {r.request_id:
           _reference_greedy(module, params, r.prompt, r.max_new_tokens)
           for r in reqs}
    for r in reqs:
        router.submit(r)
    victim = router._placed[reqs[0].request_id]
    for _ in range(2):
        router.step()
    moved = router.drain(victim)
    assert router._replicas[victim].draining
    done = {r.request_id: r for r in router.run()}
    # Zero drops, token identity for both the drained replica's in-flight
    # work and everything re-routed to the survivor.
    assert len(done) == 5 and router.stats["drained"] == 1
    assert router.stats["rerouted"] == moved
    for rid, toks in ref.items():
        assert done[rid].generated == toks, rid
    assert router._replicas[victim].engine.num_active == 0


def test_kill_reroutes_everything_with_identical_tokens(devices):
    module, params = _tiny()
    router = _router(module, params)
    rng = np.random.default_rng(53)
    shared = rng.integers(1, 512, 16).tolist()
    reqs = _shared_prefix_reqs(rng, shared, 5, new=8)
    ref = {r.request_id:
           _reference_greedy(module, params, r.prompt, r.max_new_tokens)
           for r in reqs}
    for r in reqs:
        router.submit(r)
    victim = router._placed[reqs[0].request_id]
    for _ in range(3):
        router.step()  # some requests are mid-decode on the victim
    lost = router.kill(victim)
    assert lost > 0 and router.stats["killed"] == 1
    done = {r.request_id: r for r in router.run()}
    assert len(done) == 5  # zero drops
    for rid, toks in ref.items():
        # Greedy recompute from the prompt is deterministic, so even
        # requests killed mid-generation produce identical streams.
        assert done[rid].generated == toks, rid
    survivors = [n for n in router._replicas if n != victim]
    assert all(router._replicas[n].alive for n in survivors)
