"""Real-ImageNet input pipeline: FolderDataset + native JPEG decode.

SURVEY.md §2a #3 / §7 hard part (a): the reference's ImageNet path is
ImageFolder + RandomResizedCrop/flip (train), Resize/CenterCrop (eval).
These tests run on a synthetic class-per-directory JPEG tree.
"""

import os

import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.data import native_loader
from pytorch_distributed_training_example_tpu.data.datasets import (
    IMAGENET_MEAN, IMAGENET_STD, FolderDataset, build_dataset,
    center_crop_box, random_resized_crop_params)
from pytorch_distributed_training_example_tpu.data.loader import DataLoader
from pytorch_distributed_training_example_tpu.data.sampler import ShardedSampler


def _write_jpeg(path, width, height, color=None, seed=0):
    from PIL import Image

    rng = np.random.default_rng(seed)
    if color is not None:
        arr = np.tile(np.array(color, np.uint8), (height, width, 1))
    else:
        # Smooth gradient + mild noise: JPEG-friendly, resampling-kernel
        # agnostic (PIL antialiases; the native path is plain bilinear).
        yy, xx = np.mgrid[0:height, 0:width]
        base = np.stack([xx * 255 / max(width - 1, 1),
                         yy * 255 / max(height - 1, 1),
                         np.full_like(xx, 128)], -1)
        arr = np.clip(base + rng.normal(0, 3, base.shape), 0, 255).astype(np.uint8)
    Image.fromarray(arr).save(path, quality=92)


@pytest.fixture(scope="module")
def jpeg_tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("imagenet_tree")
    sizes = [(96, 80), (120, 96), (64, 96), (100, 100), (80, 120), (72, 64)]
    for ci, cls in enumerate(["n01_cat", "n02_dog", "n03_fox"]):
        (root / cls).mkdir()
        for i, (w, h) in enumerate(sizes):
            _write_jpeg(str(root / cls / f"img_{i}.jpg"), w, h,
                        seed=ci * 100 + i)
    return str(root)


def test_folder_dataset_scan(jpeg_tree):
    ds = FolderDataset(jpeg_tree, train=False, image_size=32)
    assert ds.classes == ["n01_cat", "n02_dog", "n03_fox"]
    assert len(ds) == 18
    np.testing.assert_array_equal(ds.labels, np.repeat([0, 1, 2], 6))


def test_folder_eval_deterministic_and_normalized(jpeg_tree):
    ds = FolderDataset(jpeg_tree, train=False, image_size=32)
    a, b = ds[0], ds[0]
    np.testing.assert_array_equal(a["image"], b["image"])
    assert a["image"].shape == (32, 32, 3)
    assert a["image"].dtype == np.float32
    # Normalized pixel range: (x/255 - mean)/std for x in [0,255].
    lo = (0.0 - IMAGENET_MEAN) / IMAGENET_STD
    hi = (1.0 - IMAGENET_MEAN) / IMAGENET_STD
    assert (a["image"] >= lo - 1e-5).all() and (a["image"] <= hi + 1e-5).all()


def test_folder_train_augment_reseeds_per_epoch(jpeg_tree):
    ds = FolderDataset(jpeg_tree, train=True, image_size=32, seed=3)
    x0 = ds[1]["image"]
    x0_again = ds[1]["image"]
    np.testing.assert_array_equal(x0, x0_again)  # deterministic within epoch
    ds.epoch = 1
    x1 = ds[1]["image"]
    assert np.abs(x0 - x1).max() > 1e-3  # crop moved


def test_random_resized_crop_params_in_bounds():
    rng = np.random.default_rng(0)
    for _ in range(200):
        x, y, w, h = random_resized_crop_params(rng, 120, 90)
        assert 0 <= x and x + w <= 120 and 0 <= y and y + h <= 90
        assert w > 0 and h > 0


def test_center_crop_box_matches_recipe():
    # 224 out of resize-short-256: centered square of short*224/256.
    x, y, w, h = center_crop_box(500, 400, 224)
    assert w == h == round(400 * 224 / 256)
    assert x == (500 - w) // 2 and y == (400 - h) // 2


def test_eval_decode_color_fidelity(tmp_path):
    # Flat-color image: any correct decode/crop/resize yields that color.
    p = tmp_path / "c" / "flat.jpg"
    p.parent.mkdir()
    _write_jpeg(str(p), 90, 70, color=(200, 60, 120))
    ds = FolderDataset(str(tmp_path), train=False, image_size=24)
    img = ds[0]["image"] * IMAGENET_STD + IMAGENET_MEAN  # un-normalize
    expect = np.array([200, 60, 120]) / 255.0
    assert np.abs(img.mean((0, 1)) - expect).max() < 0.03  # JPEG tolerance


def test_build_dataset_dispatches_to_folder(jpeg_tree):
    ds = build_dataset("imagenet", jpeg_tree, train=True, image_size=48)
    assert isinstance(ds, FolderDataset)
    assert ds.augment
    # train/val split layout is preferred when present
    split_root = os.path.join(jpeg_tree, "..", "split")
    os.makedirs(os.path.join(split_root, "train", "a"), exist_ok=True)
    os.makedirs(os.path.join(split_root, "val", "a"), exist_ok=True)
    _write_jpeg(os.path.join(split_root, "train", "a", "x.jpg"), 40, 40)
    _write_jpeg(os.path.join(split_root, "val", "a", "y.jpg"), 40, 40)
    tr = build_dataset("imagenet", split_root, train=True, image_size=32)
    ev = build_dataset("imagenet", split_root, train=False, image_size=32)
    assert tr.jpeg_paths[0].endswith("x.jpg")
    assert ev.jpeg_paths[0].endswith("y.jpg")


def test_folder_dataset_with_loader(jpeg_tree):
    ds = FolderDataset(jpeg_tree, train=True, image_size=32)
    dl = DataLoader(ds, batch_size=4, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4  # 18 // 4 with drop_last
    assert batches[0]["image"].shape == (4, 32, 32, 3)
    assert batches[0]["label"].dtype == np.int32


needs_native = pytest.mark.skipif(not native_loader.available(),
                                  reason="native engine unavailable")


@needs_native
def test_native_jpeg_decode_color_fidelity(tmp_path):
    p = tmp_path / "c" / "flat.jpg"
    p.parent.mkdir()
    _write_jpeg(str(p), 90, 70, color=(30, 180, 90))
    eng = native_loader.NativeBatchEngine.jpeg(
        [str(p)], 24, IMAGENET_MEAN, IMAGENET_STD, augment=False,
        num_threads=1)
    out = np.empty((1, 24, 24, 3), np.float32)
    eng.submit(0, np.array([0]), out, seed=0)
    eng.wait(0)
    assert eng.decode_errors() == 0
    img = out[0] * IMAGENET_STD + IMAGENET_MEAN
    expect = np.array([30, 180, 90]) / 255.0
    assert np.abs(img.mean((0, 1)) - expect).max() < 0.03
    eng.close()


@needs_native
def test_native_jpeg_eval_close_to_pil(jpeg_tree):
    """Native bilinear vs PIL (antialiased) on smooth images: close, not equal."""
    ds = FolderDataset(jpeg_tree, train=False, image_size=32)
    eng = native_loader.NativeBatchEngine.jpeg(
        ds.jpeg_paths, 32, IMAGENET_MEAN, IMAGENET_STD, augment=False,
        num_threads=2)
    idx = np.arange(6)
    out = np.empty((6, 32, 32, 3), np.float32)
    eng.submit(0, idx, out, seed=0)
    eng.wait(0)
    assert eng.decode_errors() == 0
    ref = np.stack([ds[int(i)]["image"] for i in idx])
    assert np.abs(out - ref).mean() < 0.08  # normalized units (std ~0.225)
    eng.close()


@needs_native
def test_native_jpeg_loader_end_to_end(jpeg_tree):
    ds = FolderDataset(jpeg_tree, train=True, image_size=32, seed=0)
    sampler = ShardedSampler(len(ds), shuffle=True, seed=0, drop_last=True)
    dl = native_loader.NativeDataLoader.jpeg(
        ds.jpeg_paths, ds.labels, sampler, batch_size=4, image_size=32,
        mean=IMAGENET_MEAN, std=IMAGENET_STD, augment=True, num_threads=2)
    dl.set_epoch(0)
    batches = list(dl)
    assert len(batches) == 4
    for b in batches:
        assert b["image"].shape == (4, 32, 32, 3)
        assert np.isfinite(b["image"]).all()
    assert dl.engine.decode_errors() == 0
    # labels follow the sampler's index order
    order = sampler.local_indices()[:4]
    np.testing.assert_array_equal(batches[0]["label"], ds.labels[order])


@needs_native
def test_native_jpeg_decode_error_counted(tmp_path):
    p = tmp_path / "c"
    p.mkdir()
    good = p / "good.jpg"
    _write_jpeg(str(good), 40, 40, color=(10, 10, 10))
    bad = p / "bad.jpg"
    bad.write_bytes(b"not a jpeg at all")
    eng = native_loader.NativeBatchEngine.jpeg(
        [str(good), str(bad)], 16, IMAGENET_MEAN, IMAGENET_STD,
        augment=False, num_threads=1)
    out = np.full((2, 16, 16, 3), 7.0, np.float32)
    eng.submit(0, np.array([0, 1]), out, seed=0)
    eng.wait(0)
    assert eng.decode_errors() == 1
    assert np.abs(out[1]).max() == 0.0  # zero-filled, not stale
    eng.close()


@pytest.mark.slow  # ~40-105s compile on the 1-core CI host (r4 suite-budget pass)
def test_resnet_trains_from_jpeg_tree(jpeg_tree, devices):
    """ResNet-50 takes real optimizer steps fed from a directory tree
    (driver-metric workload end to end, tiny shapes)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, train_loop)
    from pytorch_distributed_training_example_tpu.data import prefetch
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.parallel import (
        sharding as sharding_lib)
    from pytorch_distributed_training_example_tpu.utils.config import Config

    mesh = mesh_lib.build_mesh({"data": 8})
    ds = FolderDataset(jpeg_tree, train=True, image_size=64)
    dl = DataLoader(ds, batch_size=16, num_workers=2)
    bundle = registry.create_model("resnet50", num_classes=3, image_size=64,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(Config(lr=0.01), steps_per_epoch=1)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    state = train_loop.create_train_state(
        bundle.module, tx, bundle.input_template, mesh, rules, seed=0)
    step = jax.jit(train_loop.make_train_step(train_loop.get_task(bundle.task)),
                   donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        it = prefetch.device_prefetch(dl, mesh_lib.batch_sharding(mesh))
        for i, batch in enumerate(it):
            state, metrics = step(state, batch)
            if i == 0:
                break
    assert np.isfinite(float(metrics["loss"]))
    assert int(jax.device_get(state.step)) == 1
