import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import (
    checkpoint as ckpt_lib, mesh as mesh_lib, optim, train_loop)
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
from pytorch_distributed_training_example_tpu.utils.config import Config


def _state(mesh, strategy="dp", seed=0):
    bundle = registry.create_model("resnet_micro", num_classes=10, image_size=32,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(Config(), steps_per_epoch=10)
    rules = sharding_lib.strategy_rules(strategy, bundle.rules)
    return train_loop.create_train_state(bundle.module, tx,
                                         bundle.input_template, mesh, rules,
                                         seed=seed)


def _assert_state_equal(a, b):
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree.leaves(a.opt_state), jax.tree.leaves(b.opt_state)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_same_sharding(tmp_path, devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    state = _state(mesh)
    ck = ckpt_lib.Checkpointer(str(tmp_path))
    ck.save(state, 7, extra={"epoch": 3}, block=True)
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 7
    other = _state(mesh, seed=99)  # different init; restore must overwrite
    restored, extra = ck.restore(other)
    assert extra == {"epoch": 3}
    _assert_state_equal(state, restored)


def test_restore_across_shardings(tmp_path, devices):
    """Save under FSDP, restore under DP (topology/strategy change on resume)."""
    fsdp_mesh = mesh_lib.build_mesh({"data": 2, "fsdp": 4})
    state = _state(fsdp_mesh, "fsdp")
    ck = ckpt_lib.Checkpointer(str(tmp_path))
    ck.save(state, 1, block=True)

    dp_mesh = mesh_lib.build_mesh({"data": 8})
    template = _state(dp_mesh, "dp", seed=5)
    restored, _ = ck.restore(template)
    _assert_state_equal(state, restored)
    # restored leaves carry the *template* (DP) shardings
    for p in jax.tree.leaves(restored.params):
        assert p.sharding.is_fully_replicated


def test_restore_peak_memory_is_shardwise(tmp_path, devices):
    """FSDP restore must assemble per-shard, never np.empty(full_shape):
    peak host allocation tracks the shard size, not the model size
    (SURVEY.md §3.4/§7(b); a Llama-8B restore would otherwise need ~32GB
    per host)."""
    import flax.linen as nn

    class Big(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(4096, use_bias=False)(x)

    mesh = mesh_lib.build_mesh({"fsdp": 8})
    tx, _ = optim.build_optimizer(Config(), steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("fsdp", {})
    template_args = (Big(), tx, (jnp.zeros((2, 4096), jnp.float32),), mesh,
                     rules)
    state = train_loop.create_train_state(*template_args, seed=0)
    kernel = state.params["Dense_0"]["kernel"]
    full_bytes = 4096 * 4096 * 4  # 64MB; 1/8 shard = 8MB
    assert not kernel.sharding.is_fully_replicated  # big enough to shard

    ck = ckpt_lib.Checkpointer(str(tmp_path))
    ck.save(state, 1, block=True)

    # Record every host buffer the restore path allocates: the old
    # implementation np.empty'd each leaf's GLOBAL shape; shard-wise restore
    # must never materialize more than one shard per buffer. (tracemalloc is
    # unusable here: on the fake-CPU backend device_put aliases host buffers,
    # so the restored state itself would dominate the numbers.)
    allocated = []
    real_empty = ckpt_lib.np.empty

    def tracking_empty(shape, *a, **kw):
        arr = real_empty(shape, *a, **kw)
        allocated.append(arr.nbytes)
        return arr

    template = train_loop.create_train_state(*template_args, seed=7)
    monkeypatch = pytest.MonkeyPatch()
    monkeypatch.setattr(ckpt_lib.np, "empty", tracking_empty)
    try:
        restored, _ = ck.restore(template)
    finally:
        monkeypatch.undo()
    assert allocated, "restore allocated no tracked host buffers"
    assert max(allocated) <= full_bytes // 8, (max(allocated), full_bytes)
    _assert_state_equal(state, restored)


def test_uncommitted_checkpoint_ignored(tmp_path, devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    state = _state(mesh)
    ck = ckpt_lib.Checkpointer(str(tmp_path))
    ck.save(state, 1, block=True)
    ck.save(state, 2, block=True)
    os.remove(os.path.join(str(tmp_path), "step_00000002", ckpt_lib.COMMIT_FILE))
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 1


def test_restore_rejects_foreign_checkpoint(tmp_path, devices):
    """A checkpoint sharing zero parameters with the model must raise, not
    silently evaluate/train a fresh init (wrong --model/--resume pairing)."""
    import flax.linen as nn

    mesh = mesh_lib.build_mesh({"data": 8})
    state = _state(mesh)
    ck = ckpt_lib.Checkpointer(str(tmp_path))
    ck.save(state, 1, block=True)

    class Other(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(4, name="totally_different")(x)

    tx, _ = optim.build_optimizer(Config(), steps_per_epoch=10)
    other = train_loop.create_train_state(
        Other(), tx, (jnp.zeros((2, 8), jnp.float32),), mesh,
        sharding_lib.strategy_rules("dp", {}), seed=0)
    with pytest.raises(ValueError, match="does not match this model"):
        ck.restore(other)
    # transfer-learning escape hatch: partial load downgrades to a warning
    restored, _ = ck.restore(other, allow_partial=True)
    assert restored is not None


def test_resave_same_step_survives_crash_window(tmp_path, devices):
    """Re-saving an already-committed step must never pass through a state
    where NO committed copy of that step exists (ADVICE r2): the old dir is
    set aside as step_X.old, and a crash between the two renames is healed
    at the next Checkpointer construction."""
    mesh = mesh_lib.build_mesh({"data": 8})
    state = _state(mesh)
    ck = ckpt_lib.Checkpointer(str(tmp_path))
    ck.save(state, 5, block=True)
    # re-save the same step: still committed and restorable afterwards
    ck.save(state, 5, block=True)
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 5
    restored, _ = ck.restore(_state(mesh, seed=9))
    _assert_state_equal(state, restored)
    assert not [n for n in os.listdir(str(tmp_path))
                if n.endswith(ckpt_lib.OLD_SUFFIX)]

    # simulate the crash landing between rename(step->old) and
    # rename(attempt->step): only the .old copy remains
    step_dir = os.path.join(str(tmp_path), "step_00000005")
    os.rename(step_dir, step_dir + ckpt_lib.OLD_SUFFIX)
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) is None
    ck2 = ckpt_lib.Checkpointer(str(tmp_path))  # startup heals it
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 5
    restored, _ = ck2.restore(_state(mesh, seed=11))
    _assert_state_equal(state, restored)


def test_prune_keeps_newest(tmp_path, devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    state = _state(mesh)
    ck = ckpt_lib.Checkpointer(str(tmp_path), keep=2)
    for s in (1, 2, 3, 4):
        ck.save(state, s, block=True)
    assert ckpt_lib.all_checkpoints(str(tmp_path)) == [3, 4]
