"""Background checkpoint re-shard (core/reshard.py): consolidation
correctness, CRC quarantine, and the save -> reshard -> restore roundtrip."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import (
    checkpoint as ckpt_lib, mesh as mesh_lib, optim, reshard, train_loop)
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import (
    sharding as sharding_lib)
from pytorch_distributed_training_example_tpu.utils.config import Config


def _write_step(directory, step=5, *, extra=None, torn_region=False):
    """Handcraft a committed multi-region checkpoint: one matrix leaf split
    into two row regions (the second announced via a per-host ``files.p*``
    sentinel, exercising the same union restore performs) plus a scalar."""
    step_dir = os.path.join(directory, f"step_{step:08d}")
    arrays = os.path.join(step_dir, "arrays")
    os.makedirs(arrays)
    w = np.arange(12, dtype=np.float32).reshape(4, 3)
    s = np.float32(7.25)
    np.save(os.path.join(arrays, "w.p0.0.npy"), w[:2])
    np.save(os.path.join(arrays, "w.p1.0.npy"), w[2:])
    np.save(os.path.join(arrays, "s.p0.0.npy"), s)

    def crc(name):
        return reshard._file_crc32(os.path.join(arrays, name))

    manifest = {
        "step": step,
        "extra": dict(extra or {"epoch": 3, "global_batch_size": 16}),
        "geometry": {"process_count": 2, "device_count": 4},
        "leaves": {
            "params/w": {"shape": [4, 3], "dtype": "float32", "files": [
                {"file": "w.p0.0.npy", "index": [[0, 2], [0, 3]],
                 "crc32": crc("w.p0.0.npy")}]},
            "params/s": {"shape": [], "dtype": "float32", "files": [
                {"file": "s.p0.0.npy", "index": [[0, 0]],
                 "crc32": crc("s.p0.0.npy")}]},
        },
    }
    with open(os.path.join(step_dir, "files.p1.json"), "w") as fh:
        json.dump({"params/w": [
            {"file": "w.p1.0.npy", "index": [[2, 4], [0, 3]],
             "crc32": crc("w.p1.0.npy")}]}, fh)
    if torn_region:
        with open(os.path.join(arrays, "w.p1.0.npy"), "r+b") as fh:
            fh.seek(0, os.SEEK_END)
            fh.truncate(fh.tell() - 4)  # host died mid-write
    with open(os.path.join(step_dir, reshard.MANIFEST_FILE), "w") as fh:
        json.dump(manifest, fh)
    with open(os.path.join(step_dir, reshard.COMMIT_FILE), "w") as fh:
        fh.write(str(step))
    return step_dir, w, s


def test_reshard_consolidates_regions_and_preserves_extra(tmp_path):
    d = str(tmp_path)
    step_dir, w, s = _write_step(d, extra={"epoch": 9, "lr": 0.1})
    assert reshard.main(["--checkpoint-dir", d, "--world", "2"]) == 0

    man = json.load(open(os.path.join(step_dir, reshard.MANIFEST_FILE)))
    # The saving-geometry record elastic planning reads is untouched...
    assert man["extra"] == {"epoch": 9, "lr": 0.1}
    assert man["step"] == 5
    # ...while the on-disk layout is one contiguous full-leaf file per array.
    assert man["geometry"] == {"process_count": 1, "device_count": 2}
    assert man["resharded"] == {
        "world": 2,
        "source_geometry": {"process_count": 2, "device_count": 4}}
    for path, meta in man["leaves"].items():
        assert len(meta["files"]) == 1, path
        (entry,) = meta["files"]
        fpath = os.path.join(step_dir, "arrays", entry["file"])
        assert reshard._file_crc32(fpath) == entry["crc32"]
    np.testing.assert_array_equal(
        np.load(os.path.join(step_dir, "arrays",
                             man["leaves"]["params/w"]["files"][0]["file"])),
        w)
    np.testing.assert_array_equal(
        np.load(os.path.join(step_dir, "arrays",
                             man["leaves"]["params/s"]["files"][0]["file"])),
        s)
    # Still committed, no attempt/set-aside dirs left behind.
    assert os.path.exists(os.path.join(step_dir, reshard.COMMIT_FILE))
    assert sorted(n for n in os.listdir(d) if n.startswith("step_")) == [
        "step_00000005"]

    # Idempotent: a second pass short-circuits instead of rewriting.
    before = os.stat(os.path.join(step_dir, reshard.MANIFEST_FILE)).st_mtime_ns
    assert reshard.main(["--checkpoint-dir", d, "--world", "2"]) == 0
    after = os.stat(os.path.join(step_dir, reshard.MANIFEST_FILE)).st_mtime_ns
    assert after == before


def test_reshard_quarantines_corrupt_source(tmp_path, caplog):
    d = str(tmp_path)
    step_dir, _, _ = _write_step(d, torn_region=True)
    with caplog.at_level("ERROR", logger="pdtx"):
        assert reshard.main(["--checkpoint-dir", d, "--world", "1"]) == 4
    # A torn source must never launder into a fresh-looking copy: the step
    # is set aside resume-ineligible, and no output was committed.
    assert not os.path.exists(step_dir)
    assert os.path.isdir(step_dir + ".corrupt")
    assert reshard.committed_steps(d) == []
    assert any("FAILED verification" in r.message for r in caplog.records)


def test_reshard_exits_3_when_nothing_committed(tmp_path):
    d = str(tmp_path)
    assert reshard.main(["--checkpoint-dir", d, "--world", "2"]) == 3
    step_dir, _, _ = _write_step(d)
    os.unlink(os.path.join(step_dir, reshard.COMMIT_FILE))  # uncommitted
    assert reshard.main(["--checkpoint-dir", d, "--world", "2"]) == 3
    # An explicit --step that is not committed is refused too.
    with open(os.path.join(step_dir, reshard.COMMIT_FILE), "w") as fh:
        fh.write("5")
    assert reshard.main(["--checkpoint-dir", d, "--world", "2",
                         "--step", "99"]) == 3


def test_reshard_picks_newest_committed_step(tmp_path):
    d = str(tmp_path)
    _write_step(d, step=3)
    step_dir, _, _ = _write_step(d, step=8)
    assert reshard.committed_steps(d) == [3, 8]
    assert reshard.main(["--checkpoint-dir", d, "--world", "2"]) == 0
    assert "resharded" in json.load(
        open(os.path.join(step_dir, reshard.MANIFEST_FILE)))
    assert "resharded" not in json.load(
        open(os.path.join(d, "step_00000003", reshard.MANIFEST_FILE)))


def test_save_reshard_restore_roundtrip(tmp_path, devices):
    """The drill path end to end: an FSDP save is consolidated by the
    background process, then restored bit-exact at a different topology."""
    d = str(tmp_path)
    bundle = registry.create_model("resnet_micro", num_classes=10,
                                   image_size=32, dtype=jnp.float32,
                                   param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(Config(), steps_per_epoch=10)
    fsdp_mesh = mesh_lib.build_mesh({"data": 2, "fsdp": 4})
    state = train_loop.create_train_state(
        bundle.module, tx, bundle.input_template, fsdp_mesh,
        sharding_lib.strategy_rules("fsdp", bundle.rules), seed=0)
    ckpt_lib.Checkpointer(d).save(state, 2, extra={"epoch": 1}, block=True)

    assert reshard.main(["--checkpoint-dir", d, "--world", "8"]) == 0

    dp_mesh = mesh_lib.build_mesh({"data": 8})
    template = train_loop.create_train_state(
        bundle.module, tx, bundle.input_template, dp_mesh,
        sharding_lib.strategy_rules("dp", bundle.rules), seed=99)
    restored, extra = ckpt_lib.Checkpointer(d).restore(template)
    assert extra == {"epoch": 1}
    import jax

    for x, y in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
