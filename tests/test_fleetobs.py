"""Fleet observability (utils/fleetobs.py + benchmarks/trace_merge.py):
clock-aligned cross-host trace merge, straggler attribution, flight
recorder, live metrics surface, artifact identity. Everything here is
jax-free — the same property the modules themselves promise."""

import json
import os
import sys
import urllib.request

import pytest

from pytorch_distributed_training_example_tpu.utils import chaos as chaos_lib
from pytorch_distributed_training_example_tpu.utils import fleetobs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import trace_merge  # noqa: E402


# ---------------------------------------------------------------------------
# Fixture builders: a synthetic 2-host x 2-attempt artifact directory.
# ---------------------------------------------------------------------------

RUN = "run-aabbcc"


def _trace_doc(host, rank, attempt, wall_origin, spans, run_id=RUN):
    """A telemetry-shaped trace file: otherData FIRST (the salvage contract),
    spans as (name, start_us, dur_us) complete events."""
    return {
        "otherData": {
            "schema_version": fleetobs.SCHEMA_VERSION, "run_id": run_id,
            "host": host, "rank": rank, "attempt": attempt,
            "clock_anchor": {"wall": wall_origin, "monotonic": 0.0},
        },
        "displayTimeUnit": "ms",
        "traceEvents": [
            {"name": n, "cat": "span", "ph": "X", "ts": t, "dur": d,
             "pid": 0, "tid": 1} for n, t, d in spans
        ],
    }


def _write_fleet_dir(tmp_path, *, torn_rank=None, second_run_id=None):
    """2 ranks x 2 attempts. Rank 1's monotonic origin starts 2.5 s of wall
    later than rank 0's; attempt 2 starts 10 s after attempt 1. Span layout
    is chosen so the aligned depth-0 spans interleave without overlap within
    each track. Optionally tears rank ``torn_rank``'s attempt-1 file
    mid-record, or stamps rank 1 with a different run id."""
    wall0 = 1000.0
    for rank, host, skew in ((0, "hostA", 0.0), (1, "hostB", 2.5)):
        for attempt, t_attempt in ((1, 0.0), (2, 10.0)):
            # Span starts are in each host's LOCAL monotonic us: the wall
            # anchor absorbs both the host skew and the attempt offset.
            spans = [("step", 100, 800), ("step", 1000, 800),
                     ("input_wait", 1900, 50)]
            rid = (second_run_id if (second_run_id and rank == 1) else RUN)
            doc = _trace_doc(host, rank, attempt,
                             wall0 + skew + t_attempt, spans, run_id=rid)
            path = os.path.join(tmp_path, f"trace_events.r{rank}.a{attempt}.json")
            body = json.dumps(doc)
            if torn_rank == rank and attempt == 1:
                # Kill mid-final-record: cut inside the last event dict.
                body = body[:body.rfind("{") + 12]
            with open(path, "w") as fh:
                fh.write(body)
    return wall0


# ---------------------------------------------------------------------------
# Trace merge: clock alignment + torn-tail salvage (satellite c).
# ---------------------------------------------------------------------------


def test_merge_clock_alignment_and_track_groups(tmp_path):
    """Depth-0 spans from 2 hosts x 2 attempts land on one axis, shifted by
    each file's wall anchor, and never overlap within a track group."""
    _write_fleet_dir(str(tmp_path))
    merged = trace_merge.merge_traces(str(tmp_path))
    other = merged["otherData"]
    assert other["run_ids"] == [RUN]
    assert sorted(other["track_groups"]) == ["hostA/rank0", "hostB/rank1"]
    assert other["salvaged"] == []

    spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    # Hand-computed alignment: rank 1 attempt 1's first span starts at its
    # local 100us + 2.5s host skew; attempt 2 adds the 10s attempt offset.
    b = other["track_groups"]["hostB/rank1"]
    b_steps = sorted(e["ts"] for e in spans
                     if e["pid"] == b and e["name"] == "step")
    assert b_steps == [100 + 2_500_000, 1000 + 2_500_000,
                       100 + 12_500_000, 1000 + 12_500_000]
    # Merged depth-0 spans are non-overlapping within each pid.
    for pid in other["track_groups"].values():
        xs = sorted((e["ts"], e["dur"]) for e in spans if e["pid"] == pid)
        for (t0, d0), (t1, _) in zip(xs, xs[1:]):
            assert t0 + d0 <= t1, f"overlap in pid {pid}"
    # Attempt-2 events are badged so restarts are visually attributable.
    assert any(e.get("args", {}).get("attempt") == 2 for e in spans)


def test_merge_salvages_torn_tail(tmp_path):
    """A file truncated mid-record (killed host) still contributes its header
    and every complete event — the elastic read_dead_hosts spirit."""
    _write_fleet_dir(str(tmp_path), torn_rank=1)
    merged = trace_merge.merge_traces(str(tmp_path))
    assert merged["otherData"]["salvaged"] == ["r1.a1"]
    # The torn file keeps at least its first complete span; the run id from
    # its otherData header survives (no mixed-run false positive).
    assert merged["otherData"]["run_ids"] == [RUN]
    b = merged["otherData"]["track_groups"]["hostB/rank1"]
    torn_spans = [e for e in merged["traceEvents"]
                  if e.get("ph") == "X" and e["pid"] == b
                  and "attempt" not in e.get("args", {})]
    assert 1 <= len(torn_spans) < 3


def test_merge_refuses_mixed_runs(tmp_path):
    _write_fleet_dir(str(tmp_path), second_run_id="run-other")
    with pytest.raises(SystemExit):
        trace_merge.merge_traces(str(tmp_path))
    merged = trace_merge.merge_traces(str(tmp_path), allow_mixed_run=True)
    assert sorted(merged["otherData"]["run_ids"]) == [RUN, "run-other"]


def test_merge_cli_writes_all_artifacts(tmp_path):
    _write_fleet_dir(str(tmp_path))
    # Goodput + steprows alongside the traces so the CLI exercises all three.
    for rank in (0, 1):
        fleetobs.write_json_atomic(
            os.path.join(str(tmp_path), f"goodput.r{rank}.a2.json"),
            {"run_id": RUN, "wall_s": 20.0, "attempts": 2,
             "categories_s": {"step": 16.0, "restart": 2.0},
             "goodput_fraction": 0.8, "coverage": 0.9,
             "meta": {"host": f"host{rank}"}})
        w = fleetobs.StepRowWriter(str(tmp_path), rank, 1)
        for s in range(4):
            w.add({"step": s, "total_s": 0.1, "input_wait_s": 0.0,
                   "compute_s": 0.1, "checkpoint_s": 0.0})
        w.flush()
    assert trace_merge.main([str(tmp_path)]) == 0
    merged = json.load(open(os.path.join(str(tmp_path), "merged_trace.json")))
    assert len(merged["otherData"]["track_groups"]) == 2
    fleet = json.load(open(os.path.join(str(tmp_path), "fleet_goodput.json")))
    assert fleet["ranks"] == [0, 1] and fleet["attempts"] == 2
    # Mean of identical per-rank decompositions == the decomposition;
    # coverage recomputed from it: (16 + 2) / 20.
    assert fleet["coverage"] == pytest.approx(0.9)
    assert fleet["goodput_fraction"] == pytest.approx(0.8)
    assert os.path.exists(os.path.join(str(tmp_path), "straggler.jsonl"))


# ---------------------------------------------------------------------------
# Straggler attribution.
# ---------------------------------------------------------------------------


def _rows(rank, stall_step=None, stall_s=1.0, n=8, base=0.1):
    rows = []
    for s in range(n):
        iw = stall_s if s == stall_step else 0.005
        rows.append({"step": s, "total_s": base + (iw - 0.005),
                     "input_wait_s": iw, "compute_s": base - 0.005,
                     "checkpoint_s": 0.0})
    return rows


def test_detect_stragglers_attributes_input_wait():
    """Collectives equalize totals; the stalled rank is found via its
    host-local input_wait excess, not the (identical) total."""
    rows0 = _rows(0)
    rows1 = _rows(1, stall_step=5)
    # Gang effect: rank 0's total at the stall step matches rank 1's.
    rows0[5]["total_s"] = rows1[5]["total_s"]
    out = fleetobs.detect_stragglers({0: rows0, 1: rows1})
    flagged = [r for r in out if r["flagged"]]
    assert len(flagged) == 1
    row = flagged[0]
    assert row["step"] == 5 and row["slowest_rank"] == 1
    assert row["cause"] == "input_wait_s"
    assert row["attribution"]["input_wait_s"] == pytest.approx(0.995)


def test_detect_stragglers_quiet_on_balanced_fleet():
    out = fleetobs.detect_stragglers({0: _rows(0), 1: _rows(1)})
    assert out and not any(r["flagged"] for r in out)


def test_detect_stragglers_total_fallback_device_skew():
    """No local component elevated -> genuine device skew: slowest total."""
    rows0, rows1 = _rows(0), _rows(1)
    rows1[3]["total_s"] = 0.5  # slower step, flat input_wait/checkpoint
    rows1[3]["compute_s"] = 0.5 - rows1[3]["input_wait_s"]
    out = {r["step"]: r for r in fleetobs.detect_stragglers(
        {0: rows0, 1: rows1})}
    assert out[3]["flagged"] and out[3]["slowest_rank"] == 1
    assert out[3]["cause"] == "compute_s"


def test_straggler_monitor_warns_and_keeps_baseline():
    mon = fleetobs.StragglerMonitor(threshold=2.0, min_window=3)
    for s in range(5):
        assert mon.observe(s, total_s=0.1, input_wait_s=0.005) is None
    reason = mon.observe(5, total_s=1.1, input_wait_s=1.0)
    assert reason is not None and "input_wait" in reason
    # The stall was recorded after the check: the next normal step is clean.
    assert mon.observe(6, total_s=0.1, input_wait_s=0.005) is None
    assert mon.warnings == 1


# ---------------------------------------------------------------------------
# Step rows: buffered writes, attempt override, torn tolerance.
# ---------------------------------------------------------------------------


def test_steprows_later_attempt_overrides_replayed_steps(tmp_path):
    w1 = fleetobs.StepRowWriter(str(tmp_path), 0, 1)
    for s in range(4):
        w1.add({"step": s, "total_s": 0.1})
    w1.flush()
    w2 = fleetobs.StepRowWriter(str(tmp_path), 0, 2)  # resume replays 2..3
    for s in (2, 3, 4):
        w2.add({"step": s, "total_s": 0.2})
    w2.flush()
    rows = fleetobs.load_steprows(str(tmp_path))[0]
    assert [r["step"] for r in rows] == [0, 1, 2, 3, 4]
    assert rows[2]["total_s"] == 0.2 and rows[0]["total_s"] == 0.1


def test_steprows_torn_tail_skipped(tmp_path):
    w = fleetobs.StepRowWriter(str(tmp_path), 0, 1)
    for s in range(3):
        w.add({"step": s, "total_s": 0.1})
    w.flush()
    with open(w.path, "a") as fh:
        fh.write('{"step": 3, "total_s"')  # killed mid-append
    rows = fleetobs.load_steprows(str(tmp_path))[0]
    assert [r["step"] for r in rows] == [0, 1, 2]


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path):
    rec = fleetobs.FlightRecorder(capacity=4)
    for s in range(10):
        rec.record_timing(s, total_s=0.1)
    rec.record_health(9, {"loss": 1.5, "arr": [1, 2]})  # arr: non-scalar out
    assert len(rec) == 4
    assert [r["step"] for r in rec.rows()] == [6, 7, 8, 9]
    assert rec.rows()[-1]["loss"] == 1.5 and "arr" not in rec.rows()[-1]

    p1 = rec.dump(str(tmp_path), reason="anomaly", meta={"step": 9})
    p2 = rec.dump(str(tmp_path), reason="preempt")  # append, not clobber
    assert p1 == p2
    lines = [json.loads(ln) for ln in open(p1)]
    headers = [ln for ln in lines if "flightrec" in ln]
    assert [h["flightrec"] for h in headers] == ["anomaly", "preempt"]
    assert headers[0]["records"] == 4
    assert len(lines) == 2 + 8


def test_dump_active_registry(tmp_path):
    rec = fleetobs.FlightRecorder(capacity=8)
    rec.record_timing(3, total_s=0.1)
    fleetobs.set_active(rec, str(tmp_path), rank=1, meta={"run_id": RUN})
    try:
        path = fleetobs.dump_active("host_loss", step=3)
        assert path and path.endswith("flightrec.r1.jsonl")
        header = json.loads(open(path).readline())
        assert header["flightrec"] == "host_loss"
        assert header["run_id"] == RUN and header["step"] == 3
    finally:
        fleetobs.set_active(None)
    assert fleetobs.dump_active("host_loss") is None


# ---------------------------------------------------------------------------
# Artifact identity + progress.
# ---------------------------------------------------------------------------


def test_ensure_run_id_stable_across_attempts_fresh_replaces(tmp_path):
    d = str(tmp_path)
    rid = fleetobs.ensure_run_id(d, "attempt-1", fresh=True, rank=0)
    assert rid == "attempt-1"
    # Resumed attempt keeps the original id; a rank>0 reads the same.
    assert fleetobs.ensure_run_id(d, "attempt-2", fresh=False, rank=0) == rid
    assert fleetobs.ensure_run_id(d, "attempt-2", rank=1) == rid
    # A fresh run replaces the stale id from the previous experiment.
    assert fleetobs.ensure_run_id(d, "new-run", fresh=True, rank=0) == "new-run"


def test_ensure_run_id_rank_nonzero_never_creates(tmp_path):
    d = str(tmp_path)
    rid = fleetobs.ensure_run_id(d, "r1-fallback", rank=1, timeout_s=0.2)
    assert rid == "r1-fallback"
    assert not os.path.exists(os.path.join(d, fleetobs.RUN_ID_FILE))


def test_write_progress_atomic_and_stamped(tmp_path):
    path = fleetobs.write_progress(str(tmp_path), {"step": 7, "loss": 2.0})
    data = json.load(open(path))
    assert data["step"] == 7
    assert data["schema_version"] == fleetobs.SCHEMA_VERSION
    assert not [n for n in os.listdir(str(tmp_path)) if ".tmp." in n]


def test_check_regression_goodput_rejects_mixed_run(tmp_path):
    import check_regression as cr

    path = os.path.join(str(tmp_path), "fleet_goodput.json")
    base = {"wall_s": 10.0, "coverage": 0.99,
            "categories_s": {"step": 9.9}, "attempts": 1}
    fleetobs.write_json_atomic(path, {**base, "run_ids": [RUN, "run-other"]})
    failures, report = cr.check_goodput(path)
    assert failures and any("MIXED-RUN" in ln for ln in report)
    fleetobs.write_json_atomic(path, {**base, "run_ids": [RUN]})
    failures, _ = cr.check_goodput(path)
    assert not failures


# ---------------------------------------------------------------------------
# Live metrics surface.
# ---------------------------------------------------------------------------


def test_metrics_server_prometheus_and_progress():
    srv = fleetobs.MetricsServer(port=0, addr="127.0.0.1").start()
    try:
        srv.update(step=42, loss=1.25, bad=float("nan"),
                   run_id=RUN, skipped_none=None, flag=True)
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
        text = body.decode()
        assert "pdtx_step 42.0" in text and "pdtx_loss 1.25" in text
        assert "pdtx_bad NaN" in text  # Prometheus non-finite spelling
        assert f'run_id="{RUN}"' in text  # info labels, not a gauge
        assert "skipped_none" not in text and "flag" not in text
        prog = json.loads(urllib.request.urlopen(
            f"{base}/progress", timeout=5).read())
        assert prog["step"] == 42.0 and prog["run_id"] == RUN
        err = urllib.request.urlopen  # 404 on unknown paths
        with pytest.raises(Exception):
            err(f"{base}/nope", timeout=5)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Chaos spec rank qualifier (the straggler drill's targeting mechanism).
# ---------------------------------------------------------------------------


def test_chaos_spec_rank_qualifier():
    evs = chaos_lib.parse_spec("loader_stall@batch=5:rank=1,sigterm@step=9")
    assert evs[0].rank == 1 and evs[0].value == 5
    assert evs[1].rank is None
    with pytest.raises(ValueError):
        chaos_lib.parse_spec("loader_stall@batch=5:rank=x")
    with pytest.raises(ValueError):
        chaos_lib.parse_spec("loader_stall@batch=5:frank=1")


def test_chaos_rank_qualifier_gates_firing(monkeypatch, tmp_path):
    monkeypatch.setenv("PROCESS_ID", "0")
    eng = chaos_lib.ChaosEngine("loader_stall@batch=2:rank=1",
                                log_dir=str(tmp_path))
    eng.STALL_S = 0.01
    batch = {"x": [0.0]}
    assert eng.batch_hook(0, 2, batch) is batch  # rank 0: no fire
    assert not eng.events[0].fired
    eng2 = chaos_lib.ChaosEngine("loader_stall@batch=2:rank=1",
                                 log_dir=str(tmp_path), rank=1)
    eng2.STALL_S = 0.01
    eng2.batch_hook(0, 2, batch)
    assert eng2.events[0].fired


def test_ensure_run_id_reclaims_torn_file_loudly(tmp_path, caplog):
    d = str(tmp_path)
    path = os.path.join(d, fleetobs.RUN_ID_FILE)
    with open(path, "w") as fh:
        fh.write('{"run_id": "killed-mid-wr')  # torn by a dead attempt
    with caplog.at_level("ERROR", logger="pdtx"):
        rid = fleetobs.ensure_run_id(d, "fresh-attempt", rank=0)
    # Rank 0 reclaims: unlink + exclusive re-create under the new id,
    # instead of poll-reading its own torn file to the deadline.
    assert rid == "fresh-attempt"
    assert json.load(open(path))["run_id"] == "fresh-attempt"
    assert any("torn" in r.message and "reclaiming" in r.message
               for r in caplog.records)


def test_ensure_run_id_rank_nonzero_times_out_on_torn_file(tmp_path, caplog):
    d = str(tmp_path)
    path = os.path.join(d, fleetobs.RUN_ID_FILE)
    with open(path, "w") as fh:
        fh.write("not json")
    with caplog.at_level("ERROR", logger="pdtx"):
        rid = fleetobs.ensure_run_id(d, "fb", rank=1, timeout_s=0.2)
    # Rank>0 never creates or reclaims — it falls back per-process, loudly.
    assert rid == "fb"
    assert open(path).read() == "not json"
    assert any("unreadable past" in r.message for r in caplog.records)


def test_read_chronic_straggler_streaks_and_resets(tmp_path):
    path = str(tmp_path / fleetobs.STRAGGLER_FILE)

    def write(rows):
        with open(path, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")

    flag = lambda rank, flagged=True: {  # noqa: E731
        "step": 1, "slowest_rank": rank, "flagged": flagged}

    assert fleetobs.read_chronic_straggler(path, 2) is None  # missing file

    # Meta rows (no flagged/slowest_rank keys) are invisible to the streak.
    write([{"schema_version": 1}, flag(1), flag(1), {"note": "x"}, flag(1)])
    got = fleetobs.read_chronic_straggler(path, 3)
    assert got == {"rank": 1, "streak": 3, "rows": 3}

    # An unflagged row resets; so does a culprit change.
    write([flag(1), flag(1), flag(1, flagged=False), flag(1)])
    assert fleetobs.read_chronic_straggler(path, 2) is None
    write([flag(1), flag(1), flag(0), flag(0)])
    got = fleetobs.read_chronic_straggler(path, 2)
    assert got == {"rank": 0, "streak": 2, "rows": 4}

    # Streak must be TRAILING: chronic history ended by a clean row is stale.
    write([flag(1), flag(1), flag(1), flag(1, flagged=False)])
    assert fleetobs.read_chronic_straggler(path, 3) is None
