"""graftlint: AST rules on synthetic fixtures, IR rules on tiny planted
programs, the whole-tree gate, and the check_regression --lint CLI."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import graftlint  # noqa: E402

PKG = graftlint.PKG
FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures")


def lint_tree(tmp_path, files):
    """Write {relpath: source} under tmp_path and run the AST layer."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return graftlint.run_ast(str(tmp_path))


def rules(findings):
    return sorted(f.rule for f in findings)


# -- GL001: zero-copy snapshots escaping to threads -------------------------

def test_gl001_r11_fixture_flagged():
    """The historical corruption class must be caught, by rule ID."""
    found = graftlint.run_ast(FIXTURE_DIR, files=["r11_zero_copy_save.py"])
    assert [f.rule for f in found] == ["GL001"]
    assert "np.asarray" in found[0].message
    assert found[0].scope == "BrokenCheckpointer.save"


def test_gl001_fixed_shape_is_clean(tmp_path):
    """The post-r11 np.array copy must NOT be flagged."""
    src = (open(os.path.join(FIXTURE_DIR, "r11_zero_copy_save.py")).read()
           .replace("np.asarray(sh.data)", "np.array(sh.data)"))
    found = lint_tree(tmp_path, {"mod.py": src})
    assert rules(found) == []


def test_gl001_direct_assignment_into_closure(tmp_path):
    found = lint_tree(tmp_path, {"mod.py": """
        import threading
        import numpy as np

        def save(arrs):
            shards = {}
            for k, a in arrs.items():
                shards[k] = np.asarray(a.data)

            def write():
                for k, v in shards.items():
                    pass

            threading.Thread(target=write).start()
    """})
    assert rules(found) == ["GL001"]


def test_gl001_consumed_by_call_not_flagged(tmp_path):
    """str(np.asarray(x).dtype) stores no buffer; memoryview in a dict that
    never reaches a thread is fine too."""
    found = lint_tree(tmp_path, {"mod.py": """
        import threading
        import numpy as np

        def save(arrs):
            meta = {}
            local = {}
            for k, a in arrs.items():
                meta[k] = str(np.asarray(a).dtype)
                local[k] = np.asarray(a)  # never read by the thread

            def write():
                for k in meta:
                    pass

            threading.Thread(target=write).start()
    """})
    assert rules(found) == []


# -- GL002: fs ops bypassing retriable_io -----------------------------------

def test_gl002_bare_fs_op_flagged(tmp_path):
    found = lint_tree(tmp_path, {f"{PKG}/core/checkpoint.py": """
        import os

        def commit(path, step):
            with open(path, "w") as fh:
                fh.write(str(step))
            os.rename(path, path + ".done")
    """})
    assert rules(found) == ["GL002", "GL002"]


def test_gl002_wrapped_function_exempt(tmp_path):
    found = lint_tree(tmp_path, {f"{PKG}/core/checkpoint.py": """
        import os
        from pytorch_distributed_training_example_tpu.utils import resilience

        def write_commit(path, step):
            with open(path, "w") as fh:
                fh.write(str(step))
            os.rename(path, path + ".done")

        def commit(path, step):
            resilience.retriable_io(write_commit, path, step,
                                    _what="ckpt_commit")
    """})
    assert rules(found) == []


def test_gl002_other_paths_not_in_scope(tmp_path):
    found = lint_tree(tmp_path, {f"{PKG}/data/loader.py": """
        def read(path):
            with open(path) as fh:
                return fh.read()
    """})
    assert rules(found) == []


# -- GL003: host-sync in step-scope modules ---------------------------------

def test_gl003_sync_primitives_flagged(tmp_path):
    found = lint_tree(tmp_path, {f"{PKG}/ops/myop.py": """
        import jax

        def bad_metrics(x):
            v = jax.device_get(x)
            w = x.item()
            x.block_until_ready()
            return v, w
    """})
    assert rules(found) == ["GL003", "GL003", "GL003"]
    assert all(f.severity == "error" for f in found)


def test_gl003_float_of_computed_is_info_and_main_exempt(tmp_path):
    found = lint_tree(tmp_path, {f"{PKG}/parallel/mine.py": """
        import jax
        import jax.numpy as jnp

        def log_loss(metrics):
            return float(metrics["loss"])

        def main():
            x = jnp.ones(())
            jax.block_until_ready(x)  # CLI self-test: exempt
    """})
    assert [(f.rule, f.severity) for f in found] == [("GL003", "info")]


# -- GL004: knob-threading consistency --------------------------------------

GL004_CONFIG = f"""
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Config:
        lr: float = 0.1
        momentum: float = 0.9
"""


def test_gl004_missing_flag_and_orphan_dest(tmp_path):
    found = lint_tree(tmp_path, {
        f"{PKG}/utils/config.py": GL004_CONFIG,
        "main.py": """
            import argparse

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--lr", type=float, default=None)
                p.add_argument("--learning-rte", type=float, default=None)
                return p
        """,
    })
    msgs = sorted(f.message for f in found)
    assert len(found) == 2 and all(f.rule == "GL004" for f in found)
    assert "'learning_rte' is not a Config field" in msgs[0]
    assert "'momentum' has no main.py CLI flag" in msgs[1]


def test_gl004_complete_threading_is_clean(tmp_path):
    found = lint_tree(tmp_path, {
        f"{PKG}/utils/config.py": GL004_CONFIG,
        "main.py": """
            import argparse

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--lr", type=float, default=None)
                p.add_argument("--momentum", type=float, default=None)
                return p
        """,
    })
    assert rules(found) == []


def test_gl004_perf_knob_must_reach_bench_cli(tmp_path):
    found = lint_tree(tmp_path, {
        f"{PKG}/utils/config.py": GL004_CONFIG,
        "main.py": """
            import argparse

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--lr", type=float, default=None)
                p.add_argument("--momentum", type=float, default=None)
                return p
        """,
        "bench.py": """
            import argparse

            def setup_step(model, momentum=0.9):
                pass

            def main():
                p = argparse.ArgumentParser()
                p.add_argument("--model", default="resnet18")
                args = p.parse_args()
                setup_step(args.model)
        """,
    })
    assert rules(found) == ["GL004"]
    assert "perf knob 'momentum'" in found[0].message


def test_gl004_renamed_dest_traced_through_kwarg(tmp_path):
    """bench.py threads --mom via setup_step(momentum=args.mom): reachable."""
    found = lint_tree(tmp_path, {
        f"{PKG}/utils/config.py": GL004_CONFIG,
        "main.py": """
            import argparse

            def build_parser():
                p = argparse.ArgumentParser()
                p.add_argument("--lr", type=float, default=None)
                p.add_argument("--momentum", type=float, default=None)
                return p
        """,
        "bench.py": """
            import argparse

            def setup_step(model, momentum=0.9):
                pass

            def main():
                p = argparse.ArgumentParser()
                p.add_argument("--model", default="resnet18")
                p.add_argument("--mom", type=float, default=0.9)
                args = p.parse_args()
                setup_step(args.model, momentum=args.mom)
        """,
    })
    assert rules(found) == []


# -- GL005: wall-clock / unseeded randomness --------------------------------

def test_gl005_unseeded_randomness_flagged(tmp_path):
    found = lint_tree(tmp_path, {f"{PKG}/utils/chaos.py": """
        import random
        import time

        import numpy as np

        def jitter():
            return time.time() + random.random() + np.random.uniform()
    """})
    assert rules(found) == ["GL005", "GL005", "GL005"]


def test_gl005_seeded_generators_clean(tmp_path):
    found = lint_tree(tmp_path, {f"{PKG}/data/sampler.py": """
        import time

        import numpy as np

        def order(seed, epoch, n):
            rng = np.random.default_rng((seed, epoch))
            t0 = time.monotonic()  # durations are fine, wall-clock isn't
            return rng.permutation(n), t0
    """})
    assert rules(found) == []


# -- IR rules on tiny planted programs --------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    state = {"w": jax.ShapeDtypeStruct((128, 256), jnp.float32),
             "m": jax.ShapeDtypeStruct((128, 256), jnp.float32)}
    batch = jax.ShapeDtypeStruct((8, 128), jnp.bfloat16)
    return jax, jnp, state, batch


def _step_ok(jnp):
    def step(s, b):
        g = (b @ s["w"].astype(jnp.bfloat16)).astype(jnp.float32).sum(0)
        return {"w": s["w"] - 1e-3 * g, "m": s["m"] * 0.9}, jnp.float32(0)
    return step


def test_ir_planted_missing_donation(tiny):
    jax, jnp, state, batch = tiny
    lowered = jax.jit(_step_ok(jnp)).lower(state, batch)  # no donate_argnums
    found = graftlint.lint_lowered("t", lowered, abstract_state=state)
    gl101 = [f for f in found if f.rule == "GL101"]
    assert gl101 and gl101[0].severity == "error"
    assert "not aliased" in gl101[0].message


def test_ir_donated_state_is_clean(tiny):
    jax, jnp, state, batch = tiny
    lowered = jax.jit(_step_ok(jnp), donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state)
    assert not [f for f in found if f.rule == "GL101" and f.severity == "error"]


def test_ir_planted_fp32_upcast_in_bf16_region(tiny):
    jax, jnp, state, batch = tiny

    def step(s, b):
        with jax.named_scope("moe_router"):
            h = b.astype(jnp.float32) @ s["w"]  # planted forward leak
        return {"w": s["w"] - h.sum(0) * 0, "m": s["m"]}, jnp.float32(0)

    lowered = jax.jit(step, donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state,
                                   upcast_bytes=1024)
    gl102 = [f for f in found if f.rule == "GL102"]
    assert gl102 and gl102[0].scope == "moe_router"
    assert gl102[0].severity == "error"


def test_ir_accumulating_bf16_dot_not_flagged(tiny):
    """bf16 x bf16 einsum with preferred_element_type=f32 is the
    accumulation contract working — must not be reported as a leak."""
    jax, jnp, state, batch = tiny

    def step(s, b):
        with jax.named_scope("moe_experts"):
            h = jnp.einsum("tb,bf->tf", b, s["w"].astype(jnp.bfloat16),
                           preferred_element_type=jnp.float32)
        return ({"w": s["w"] - h.astype(jnp.float32).sum(0) * 0,
                 "m": s["m"]}, jnp.float32(0))

    lowered = jax.jit(step, donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state,
                                   upcast_bytes=1024)
    assert not [f for f in found if f.rule == "GL102"]


def test_ir_host_callback_flagged(tiny):
    jax, jnp, state, batch = tiny
    from jax.experimental import io_callback

    def step(s, b):
        io_callback(lambda x: None, None, b.sum())
        return s, jnp.float32(0)

    lowered = jax.jit(step, donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state)
    gl103 = [f for f in found if f.rule == "GL103"]
    assert gl103 and gl103[0].severity == "error"


def test_ir_sharding_coverage_and_missing(tiny):
    jax, jnp, state, batch = tiny
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(jax.devices(), ("data",))

    def constrained(s, b):
        with jax.named_scope("moe_dispatch"):
            h = jax.lax.with_sharding_constraint(
                b, NamedSharding(mesh, P("data", None)))
        return s, h.astype(jnp.float32).sum()

    lowered = jax.jit(constrained, donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state,
                                   expect_sharding=True)
    gl104 = [f for f in found if f.rule == "GL104"]
    assert gl104 and gl104[0].severity == "info"
    assert "moe_dispatch=1" in gl104[0].message

    lowered = jax.jit(_step_ok(jnp), donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state,
                                   expect_sharding=True)
    gl104 = [f for f in found if f.rule == "GL104"]
    assert gl104 and gl104[0].severity == "error"


def _a2a_step(jax, jnp, mesh, scope):
    """Planted 2-device shard_map step whose body issues one all-to-all,
    optionally inside ``scope`` (GL105's sanction vocabulary)."""
    from jax.sharding import PartitionSpec as P

    def body(x):
        import contextlib
        ctx = jax.named_scope(scope) if scope else contextlib.nullcontext()
        with ctx:
            return jax.lax.all_to_all(x, "expert", split_axis=0,
                                      concat_axis=0, tiled=True)

    def step(s, b):
        y = jax.shard_map(body, mesh=mesh, in_specs=P("expert", None),
                          out_specs=P("expert", None), check_vma=False)(b)
        return s, y.astype(jnp.float32).sum()

    return step


def _cperm_step(jax, jnp, mesh, scope):
    """Planted 2-device shard_map step issuing one collective-permute,
    optionally inside ``scope`` (GL105's ring/pp sanction vocabulary)."""
    from jax.sharding import PartitionSpec as P

    def body(x):
        import contextlib
        ctx = jax.named_scope(scope) if scope else contextlib.nullcontext()
        with ctx:
            return jax.lax.ppermute(x, "context", [(0, 1), (1, 0)])

    def step(s, b):
        y = jax.shard_map(body, mesh=mesh, in_specs=P("context", None),
                          out_specs=P("context", None), check_vma=False)(b)
        return s, y.astype(jnp.float32).sum()

    return step


@pytest.mark.parametrize("scope", [None, "attn_ring_ppermute",
                                   "pp_stage_shift"])
def test_ir_cperm_scope_rule(tiny, scope):
    """GL105 (r20): an untagged collective-permute is an error; the ring
    K/V rotation and GPipe stage-hop scopes are sanctioned."""
    import numpy as np
    from jax.sharding import Mesh

    jax, jnp, state, batch = tiny
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("context",))
    lowered = jax.jit(_cperm_step(jax, jnp, mesh, scope),
                      donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state)
    gl105 = [f for f in found if f.rule == "GL105"]
    if scope is None:
        assert gl105 and gl105[0].severity == "error"
        assert "collective-permute outside sanctioned" in gl105[0].message
    else:
        assert gl105 == [], [f.render() for f in gl105]


def test_ir_sharding_seq_census(tiny):
    """GL104 (r20): on a context>1 mesh the coverage census counts
    sequence-dim constraints; zero seq anchors is an error."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    jax, jnp, state, batch = tiny
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(1, 2),
                ("data", "context"))
    b3 = jax.ShapeDtypeStruct((4, 64, 32), jnp.bfloat16)

    def seq_anchored(s, b):
        h = jax.lax.with_sharding_constraint(
            b, NamedSharding(mesh, P("data", "context", None)))
        return s, h.astype(jnp.float32).sum()

    lowered = jax.jit(seq_anchored, donate_argnums=0).lower(state, b3)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state,
                                   expect_sharding=True, seq_axis=True)
    gl104 = [f for f in found if f.rule == "GL104"]
    assert gl104 and gl104[0].severity == "info"
    assert "seq-dim=1" in gl104[0].message

    def batch_only(s, b):
        h = jax.lax.with_sharding_constraint(
            b, NamedSharding(mesh, P("data", None, None)))
        return s, h.astype(jnp.float32).sum()

    lowered = jax.jit(batch_only, donate_argnums=0).lower(state, b3)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state,
                                   expect_sharding=True, seq_axis=True)
    errs = [f for f in found if f.rule == "GL104" and f.severity == "error"]
    assert errs and "no sharding constraint splits the sequence dim" in (
        errs[0].message)


@pytest.mark.parametrize("scope", [None, "moe_dispatch", "attn_ulysses_a2a"])
def test_ir_a2a_scope_rule(tiny, scope):
    """GL105: an untagged all-to-all is an error; the MoE EP transport and
    Ulysses scopes are sanctioned (their bytes are census-attributable)."""
    import numpy as np
    from jax.sharding import Mesh

    jax, jnp, state, batch = tiny
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("expert",))
    lowered = jax.jit(_a2a_step(jax, jnp, mesh, scope),
                      donate_argnums=0).lower(state, batch)
    found = graftlint.lint_lowered("t", lowered, abstract_state=state)
    gl105 = [f for f in found if f.rule == "GL105"]
    if scope is None:
        assert gl105 and gl105[0].severity == "error"
        assert gl105[0].scope == "a2a-scope"
        assert "all-to-all outside sanctioned" in gl105[0].message
    else:
        assert gl105 == [], [f.render() for f in gl105]


# -- whole-tree gate + baseline workflow ------------------------------------

def test_whole_tree_zero_unbaselined_errors():
    findings = graftlint.run_ast(REPO)
    baseline = graftlint.load_baseline()
    unbaselined, baselined, stale = graftlint.split_findings(findings,
                                                            baseline)
    errors = [f.render() for f in unbaselined if f.severity == "error"]
    assert errors == [], "unbaselined graftlint errors:\n" + "\n".join(errors)
    assert stale == [], f"stale suppressions (refresh with --record): {stale}"


def test_baseline_has_no_unreviewed_entries():
    baseline = graftlint.load_baseline()
    assert baseline["suppressions"], "expected a non-empty reviewed baseline"
    bad = [s for s in baseline["suppressions"]
           if s.get("justification", "").startswith("UNREVIEWED")
           or not s.get("justification")]
    assert bad == [], bad


def test_record_baseline_preserves_justifications(tmp_path):
    f = graftlint.Finding(rule="GL002", path="x.py", line=3, scope="f",
                          message="m", snippet="open(p)")
    path = str(tmp_path / "b.json")
    graftlint.record_baseline([f], path)
    doc = graftlint.load_baseline(path)
    assert doc["suppressions"][0]["justification"].startswith("UNREVIEWED")
    doc["suppressions"][0]["justification"] = "reviewed: fine"
    json.dump(doc, open(path, "w"))
    graftlint.record_baseline([f], path)
    doc = graftlint.load_baseline(path)
    assert doc["suppressions"][0]["justification"] == "reviewed: fine"
    # findings match the recorded baseline -> gate passes
    unbaselined, _, stale = graftlint.split_findings([f], doc)
    assert unbaselined == [] and stale == []


# -- CLI gates (the tier-1 shell of graftlint.py + check_regression) --------

def test_cli_graftlint_ast_clean_on_head():
    res = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "graftlint.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 unbaselined error(s)" in res.stdout


def test_cli_check_regression_lint_pass_and_fail(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    res = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "check_regression.py"),
         "--lint"],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "LINT" in res.stdout

    bad_root = tmp_path / "tree"
    bad = bad_root / PKG / "core" / "checkpoint.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def f(p):\n    return open(p).read()\n")
    empty = tmp_path / "baseline.json"
    empty.write_text('{"suppressions": []}\n')
    res = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "check_regression.py"),
         "--lint", "--lint-root", str(bad_root),
         "--lint-baseline", str(empty)],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "LINT-FAIL" in res.stdout and "GL002" in res.stdout

    # --record refreshes the baseline; the same tree then gates clean.
    res = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "check_regression.py"),
         "--lint", "--lint-root", str(bad_root),
         "--lint-baseline", str(empty), "--record"],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "RECORDED" in res.stdout
    res = subprocess.run(
        [sys.executable, os.path.join("benchmarks", "check_regression.py"),
         "--lint", "--lint-root", str(bad_root),
         "--lint-baseline", str(empty)],
        cwd=REPO, capture_output=True, text=True, timeout=120, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
