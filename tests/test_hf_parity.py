"""Numerical parity against the PyTorch/HF implementations (interop proof).

Random-weight HF models are converted via models.import_hf and must produce
the same logits as our TPU-native modules — validating attention scaling,
GELU flavor, LayerNorm/RMSNorm epsilons, RoPE convention, GQA grouping, and
weight-tying against the torch reference ecosystem.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pytorch_distributed_training_example_tpu.models import (  # noqa: E402
    gpt2 as gpt2_lib, import_hf, llama as llama_lib)


def test_gpt2_logits_match_hf():
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    hf = transformers.GPT2LMHeadModel(cfg).eval()

    ours = gpt2_lib.GPT2(vocab_size=128, num_layers=2, num_heads=4,
                         d_model=64, max_seq_len=64, dropout=0.0)
    params = import_hf.to_jax(import_hf.import_gpt2(hf))

    toks = np.random.RandomState(0).randint(0, 128, (2, 32))
    with torch.no_grad():
        ref = hf(torch.tensor(toks)).logits.numpy()
    out = ours.apply({"params": params}, jnp.asarray(toks), train=False)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_llama_logits_match_hf():
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        attention_bias=False, tie_word_embeddings=False)
    hf = transformers.LlamaForCausalLM(cfg).eval()

    ours = llama_lib.Llama(
        vocab_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=64, ffn_dim=128, max_seq_len=64, rope_theta=10000.0)
    params = import_hf.to_jax(import_hf.import_llama(hf))

    toks = np.random.RandomState(1).randint(0, 128, (2, 32))
    with torch.no_grad():
        ref = hf(torch.tensor(toks)).logits.numpy()
    out = ours.apply({"params": params}, jnp.asarray(toks), train=False)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)
