"""Golden-metric regression gate (SURVEY.md §4.5) — pure-python unit tests."""

import json
import subprocess
import sys
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import check_regression as cr  # noqa: E402

GOLDEN = {"TPU v5 lite": {
    "resnet50_imagenet_train_throughput": {"value": 2200.0},
    "gpt2_lm1024_train_throughput": {"value": 100.0},
}}


def _result(resnet=2250.0, lm=105.0, device="TPU v5 lite"):
    return {
        "metric": "resnet50_imagenet_train_throughput", "value": resnet,
        "extra": {"device": device,
                  "lm": {"metric": "gpt2_lm1024_train_throughput",
                         "value": lm, "unit": "s"}},
    }


def test_ok_within_tolerance():
    failures, report = cr.check(_result(), GOLDEN)
    assert not failures
    assert sum(line.startswith("OK") for line in report) == 2


def test_headline_regression_fails():
    failures, _ = cr.check(_result(resnet=1800.0), GOLDEN)
    assert len(failures) == 1 and "resnet50" in failures[0]


def test_lm_row_regression_fails():
    failures, _ = cr.check(_result(lm=80.0), GOLDEN)
    assert len(failures) == 1 and "gpt2" in failures[0]


def test_unknown_device_never_fails():
    failures, report = cr.check(_result(resnet=1.0, device="TPU v9"), GOLDEN)
    assert not failures
    assert all(line.startswith("NO-GOLDEN") for line in report)


def test_cli_handles_driver_wrapper(tmp_path):
    """The driver's BENCH_r{N}.json wraps the line under 'parsed' and is
    pretty-printed (multi-line). Values track the REAL golden file (the
    subprocess loads it): the test is about wrapper parsing, not numbers."""
    golden = cr.load_golden()["TPU v5 lite"]
    wrapper = {"rc": 0, "parsed": _result(
        resnet=golden["resnet50_imagenet_train_throughput"]["value"],
        lm=golden["gpt2_lm1024_train_throughput"]["value"])}
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(wrapper, indent=2))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "check_regression.py"),
         str(f)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "resnet50" in proc.stdout


def test_real_golden_file_loads():
    golden = cr.load_golden()
    assert "TPU v5 lite" in golden


# ---- --aot-bytes: per-region AOT modeled-byte gate (r8) ----

AOT_GOLDEN = {"aot_regions": {"llama_moe b4 s2048 gather": {
    "backend_lowering": "cpu",
    "attribution": "proportional_bytes",
    "regions": {"moe_router": 50.0, "moe_experts": 170.0},
}}}


def _aot_result(router=50.0, experts=170.0, backend="cpu",
                attribution="proportional_bytes"):
    return {
        "mode": "aot_hlo_model", "attribution": attribution,
        "backend_lowering": backend, "model": "llama_moe",
        "per_chip_batch": 4, "seq_len": 2048,
        "moe_dispatch_impl": "gather",
        "regions": {"moe_router": {"gbytes_modeled": router},
                    "moe_experts": {"gbytes_modeled": experts}},
    }


def test_aot_bytes_ok_and_shrink_pass():
    failures, report = cr.check_aot_bytes(_aot_result(router=20.0),
                                          AOT_GOLDEN)
    assert not failures
    assert sum(line.startswith("OK") for line in report) == 2


def test_aot_bytes_growth_fails():
    """Bytes regress UPWARD: +10% is the gate, +20% must fail."""
    failures, _ = cr.check_aot_bytes(_aot_result(router=60.0), AOT_GOLDEN)
    assert len(failures) == 1 and "moe_router" in failures[0]
    failures, _ = cr.check_aot_bytes(_aot_result(router=54.9), AOT_GOLDEN)
    assert not failures


def test_aot_bytes_no_golden_reports_not_fails():
    res = _aot_result()
    res["moe_dispatch_impl"] = "sort"  # different key -> no golden entry
    failures, report = cr.check_aot_bytes(res, AOT_GOLDEN)
    assert not failures
    assert report and report[0].startswith("NO-GOLDEN")


def test_aot_bytes_skips_on_model_mismatch():
    """Goldens are lowering- and attribution-model-specific: numbers from
    a different backend or byte-attribution scheme never compare."""
    for kw in ({"backend": "tpu"}, {"attribution": "line_majority"}):
        failures, report = cr.check_aot_bytes(
            _aot_result(router=999.0, **kw), AOT_GOLDEN)
        assert not failures
        assert report and report[0].startswith("SKIP")


def test_aot_bytes_record_then_check_cli(tmp_path):
    """--record writes the golden, a second invocation gates against it;
    a grown region then fails with exit code 1."""
    golden_path = tmp_path / "golden.json"
    golden_path.write_text(json.dumps({"_comment": "test"}))
    import importlib
    res_file = tmp_path / "aot.json"
    res_file.write_text(json.dumps(_aot_result()))
    cr.record_aot_golden(json.loads(res_file.read_text()), str(golden_path))
    golden = json.loads(golden_path.read_text())
    assert "_comment" in golden  # comment keys survive the rewrite
    key = "llama_moe b4 s2048 gather"
    assert golden["aot_regions"][key]["regions"]["moe_router"] == 50.0
    ok, _ = cr.check_aot_bytes(_aot_result(),
                               cr.load_golden(str(golden_path)))
    assert not ok
    bad, _ = cr.check_aot_bytes(_aot_result(router=70.0),
                                cr.load_golden(str(golden_path)))
    assert len(bad) == 1


def test_real_golden_has_aot_regions():
    """The bench-shape golden this round recorded (PROFILE_MOE.md r8)."""
    entry = cr.load_golden()["aot_regions"]["llama_moe b4 s2048 gather"]
    assert entry["attribution"] == "proportional_bytes"
    assert entry["regions"]["moe_router"] < 60.0  # the corrected number


# ---- proportional fusion attribution (profile_step.build_op_moe_weights) --

SYNTH_HLO = """\
HloModule synth

%fused_computation.1 (param_0: f32[8]) -> f32[24] {
  %param_0 = f32[8]{0} parameter(0)
  %a.1 = f32[8]{0} add(%param_0, %param_0), metadata={op_name="jit(f)/moe_router/add"}
  ROOT %b.1 = f32[24]{0} multiply(%a.1, %a.1), metadata={op_name="jit(f)/other"}
}

ENTRY %main (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  %fusion.1 = f32[24]{0} fusion(%p), kind=kLoop, calls=%fused_computation.1, metadata={op_name="jit(f)/other"}
  ROOT %t = f32[8]{0} tanh(%p), metadata={op_name="jit(f)/moe_aux/tanh"}
}
"""


def test_moe_weights_split_mixed_fusion():
    """A fusion whose interior is 25% router bytes (32 of 128) charges the
    router exactly that fraction; the untagged remainder is unassigned.
    Tagged non-fusion ops keep weight 1.0. The winner-take-all map
    (build_op_moe_tags) would have charged this fusion 100% to the router
    — the r7 mega-fusion misattribution this model corrects."""
    import profile_step as ps

    w = ps.build_op_moe_weights(SYNTH_HLO)
    assert w["fusion.1"] == {"moe_router": 32.0 / 128.0}
    assert w["t"] == {"moe_aux": 1.0}
    # the interior tagged line is itself weighted (its own op_bytes exist)
    assert w["a.1"] == {"moe_router": 1.0}
    # contrast: the line-majority map attributes the whole fusion
    tags = ps.build_op_moe_tags(SYNTH_HLO)
    assert tags["fusion.1"] == "moe_router"


def _goodput(tmp_path, history):
    path = tmp_path / "goodput.json"
    path.write_text(json.dumps({"ttfs_history": history}))
    return str(path)


def _ttfs(mode, s, attempt=0):
    return {"attempt": attempt, "mode": mode, "ttfs_s": s}


def test_ttfs_warm_beats_cold_passes(tmp_path):
    failures, report = cr.check_ttfs(_goodput(tmp_path, [
        _ttfs("cold", 8.0), _ttfs("warm", 1.5, 1), _ttfs("cold", 9.0, 2)]))
    assert not failures
    assert any("OK" in line and "x0.19" in line for line in report)


def test_ttfs_slow_warm_fails(tmp_path):
    # Every warm attempt must beat the SLOWEST cold by the floor; warm at
    # 0.9x cold means the executable cache is not paying for itself.
    failures, report = cr.check_ttfs(
        _goodput(tmp_path, [_ttfs("cold", 8.0), _ttfs("warm", 7.2, 1)]))
    assert failures and "not paying for itself" in failures[0]
    assert any(line.startswith("REGRESSION") for line in report)
    # A looser floor admits the same history.
    failures, _ = cr.check_ttfs(
        _goodput(tmp_path, [_ttfs("cold", 8.0), _ttfs("warm", 7.2, 1)]),
        max_ratio=0.95)
    assert not failures


def test_ttfs_neutral_without_a_pair(tmp_path):
    # All-cold (cache missing/corrupt -> quarantined) is the cache layer
    # behaving correctly, not a regression.
    for history in ([_ttfs("cold", 8.0), _ttfs("cold", 8.2, 1)],
                    [_ttfs("warm", 1.0)], []):
        failures, report = cr.check_ttfs(_goodput(tmp_path, history))
        assert not failures
        assert any("neutral" in line for line in report)


def test_ttfs_malformed_goodput_fails_loudly(tmp_path):
    failures, report = cr.check_ttfs(str(tmp_path / "missing.json"))
    assert failures and any("MALFORMED" in line for line in report)
    bad = tmp_path / "goodput.json"
    bad.write_text('{"ttfs_history": [{"mode": "warm", "ttfs_s": "fast"}]}')
    failures, _ = cr.check_ttfs(str(bad))
    assert failures and "malformed ttfs_history entry" in failures[0]


def test_ttfs_cli_gate(tmp_path):
    path = _goodput(tmp_path, [_ttfs("cold", 6.0), _ttfs("warm", 1.0, 1)])
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "check_regression.py"),
         "--ttfs", path], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "check_regression.py"),
         "--ttfs", path, "--ttfs-max-ratio", "0.1"],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "REGRESSION ttfs" in proc.stdout
