"""Golden-metric regression gate (SURVEY.md §4.5) — pure-python unit tests."""

import json
import subprocess
import sys
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import check_regression as cr  # noqa: E402

GOLDEN = {"TPU v5 lite": {
    "resnet50_imagenet_train_throughput": {"value": 2200.0},
    "gpt2_lm1024_train_throughput": {"value": 100.0},
}}


def _result(resnet=2250.0, lm=105.0, device="TPU v5 lite"):
    return {
        "metric": "resnet50_imagenet_train_throughput", "value": resnet,
        "extra": {"device": device,
                  "lm": {"metric": "gpt2_lm1024_train_throughput",
                         "value": lm, "unit": "s"}},
    }


def test_ok_within_tolerance():
    failures, report = cr.check(_result(), GOLDEN)
    assert not failures
    assert sum(line.startswith("OK") for line in report) == 2


def test_headline_regression_fails():
    failures, _ = cr.check(_result(resnet=1800.0), GOLDEN)
    assert len(failures) == 1 and "resnet50" in failures[0]


def test_lm_row_regression_fails():
    failures, _ = cr.check(_result(lm=80.0), GOLDEN)
    assert len(failures) == 1 and "gpt2" in failures[0]


def test_unknown_device_never_fails():
    failures, report = cr.check(_result(resnet=1.0, device="TPU v9"), GOLDEN)
    assert not failures
    assert all(line.startswith("NO-GOLDEN") for line in report)


def test_cli_handles_driver_wrapper(tmp_path):
    """The driver's BENCH_r{N}.json wraps the line under 'parsed' and is
    pretty-printed (multi-line). Values track the REAL golden file (the
    subprocess loads it): the test is about wrapper parsing, not numbers."""
    golden = cr.load_golden()["TPU v5 lite"]
    wrapper = {"rc": 0, "parsed": _result(
        resnet=golden["resnet50_imagenet_train_throughput"]["value"],
        lm=golden["gpt2_lm1024_train_throughput"]["value"])}
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(wrapper, indent=2))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "check_regression.py"),
         str(f)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "resnet50" in proc.stdout


def test_real_golden_file_loads():
    golden = cr.load_golden()
    assert "TPU v5 lite" in golden
