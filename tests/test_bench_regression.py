"""Golden-metric regression gate (SURVEY.md §4.5) — pure-python unit tests."""

import json
import subprocess
import sys
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import check_regression as cr  # noqa: E402

GOLDEN = {"TPU v5 lite": {
    "resnet50_imagenet_train_throughput": {"value": 2200.0},
    "gpt2_lm1024_train_throughput": {"value": 100.0},
}}


def _result(resnet=2250.0, lm=105.0, device="TPU v5 lite"):
    return {
        "metric": "resnet50_imagenet_train_throughput", "value": resnet,
        "extra": {"device": device,
                  "lm": {"metric": "gpt2_lm1024_train_throughput",
                         "value": lm, "unit": "s"}},
    }


def test_ok_within_tolerance():
    failures, report = cr.check(_result(), GOLDEN)
    assert not failures
    assert sum(line.startswith("OK") for line in report) == 2


def test_headline_regression_fails():
    failures, _ = cr.check(_result(resnet=1800.0), GOLDEN)
    assert len(failures) == 1 and "resnet50" in failures[0]


def test_lm_row_regression_fails():
    failures, _ = cr.check(_result(lm=80.0), GOLDEN)
    assert len(failures) == 1 and "gpt2" in failures[0]


def test_unknown_device_never_fails():
    failures, report = cr.check(_result(resnet=1.0, device="TPU v9"), GOLDEN)
    assert not failures
    assert all(line.startswith("NO-GOLDEN") for line in report)


def test_cli_handles_driver_wrapper(tmp_path):
    """The driver's BENCH_r{N}.json wraps the line under 'parsed' and is
    pretty-printed (multi-line). Values track the REAL golden file (the
    subprocess loads it): the test is about wrapper parsing, not numbers."""
    golden = cr.load_golden()["TPU v5 lite"]
    wrapper = {"rc": 0, "parsed": _result(
        resnet=golden["resnet50_imagenet_train_throughput"]["value"],
        lm=golden["gpt2_lm1024_train_throughput"]["value"])}
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(wrapper, indent=2))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "check_regression.py"),
         str(f)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "resnet50" in proc.stdout


def test_real_golden_file_loads():
    golden = cr.load_golden()
    assert "TPU v5 lite" in golden


# ---- --aot-bytes: per-region AOT modeled-byte gate (r8) ----

AOT_GOLDEN = {"aot_regions": {"llama_moe b4 s2048 gather": {
    "backend_lowering": "cpu",
    "attribution": "proportional_bytes",
    "regions": {"moe_router": 50.0, "moe_experts": 170.0},
}}}


def _aot_result(router=50.0, experts=170.0, backend="cpu",
                attribution="proportional_bytes"):
    return {
        "mode": "aot_hlo_model", "attribution": attribution,
        "backend_lowering": backend, "model": "llama_moe",
        "per_chip_batch": 4, "seq_len": 2048,
        "moe_dispatch_impl": "gather",
        "regions": {"moe_router": {"gbytes_modeled": router},
                    "moe_experts": {"gbytes_modeled": experts}},
    }


def test_aot_bytes_ok_and_shrink_pass():
    failures, report = cr.check_aot_bytes(_aot_result(router=20.0),
                                          AOT_GOLDEN)
    assert not failures
    assert sum(line.startswith("OK") for line in report) == 2


def test_aot_bytes_growth_fails():
    """Bytes regress UPWARD: +10% is the gate, +20% must fail."""
    failures, _ = cr.check_aot_bytes(_aot_result(router=60.0), AOT_GOLDEN)
    assert len(failures) == 1 and "moe_router" in failures[0]
    failures, _ = cr.check_aot_bytes(_aot_result(router=54.9), AOT_GOLDEN)
    assert not failures


def test_aot_bytes_no_golden_reports_not_fails():
    res = _aot_result()
    res["moe_dispatch_impl"] = "sort"  # different key -> no golden entry
    failures, report = cr.check_aot_bytes(res, AOT_GOLDEN)
    assert not failures
    assert report and report[0].startswith("NO-GOLDEN")


def test_aot_bytes_skips_on_model_mismatch():
    """Goldens are lowering- and attribution-model-specific: numbers from
    a different backend or byte-attribution scheme never compare."""
    for kw in ({"backend": "tpu"}, {"attribution": "line_majority"}):
        failures, report = cr.check_aot_bytes(
            _aot_result(router=999.0, **kw), AOT_GOLDEN)
        assert not failures
        assert report and report[0].startswith("SKIP")


def test_aot_bytes_record_then_check_cli(tmp_path):
    """--record writes the golden, a second invocation gates against it;
    a grown region then fails with exit code 1."""
    golden_path = tmp_path / "golden.json"
    golden_path.write_text(json.dumps({"_comment": "test"}))
    import importlib
    res_file = tmp_path / "aot.json"
    res_file.write_text(json.dumps(_aot_result()))
    cr.record_aot_golden(json.loads(res_file.read_text()), str(golden_path))
    golden = json.loads(golden_path.read_text())
    assert "_comment" in golden  # comment keys survive the rewrite
    key = "llama_moe b4 s2048 gather"
    assert golden["aot_regions"][key]["regions"]["moe_router"] == 50.0
    ok, _ = cr.check_aot_bytes(_aot_result(),
                               cr.load_golden(str(golden_path)))
    assert not ok
    bad, _ = cr.check_aot_bytes(_aot_result(router=70.0),
                                cr.load_golden(str(golden_path)))
    assert len(bad) == 1


def test_real_golden_has_aot_regions():
    """The bench-shape golden this round recorded (PROFILE_MOE.md r8)."""
    entry = cr.load_golden()["aot_regions"]["llama_moe b4 s2048 gather"]
    assert entry["attribution"] == "proportional_bytes"
    assert entry["regions"]["moe_router"] < 60.0  # the corrected number


# ---- proportional fusion attribution (profile_step.build_op_moe_weights) --

SYNTH_HLO = """\
HloModule synth

%fused_computation.1 (param_0: f32[8]) -> f32[24] {
  %param_0 = f32[8]{0} parameter(0)
  %a.1 = f32[8]{0} add(%param_0, %param_0), metadata={op_name="jit(f)/moe_router/add"}
  ROOT %b.1 = f32[24]{0} multiply(%a.1, %a.1), metadata={op_name="jit(f)/other"}
}

ENTRY %main (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  %fusion.1 = f32[24]{0} fusion(%p), kind=kLoop, calls=%fused_computation.1, metadata={op_name="jit(f)/other"}
  ROOT %t = f32[8]{0} tanh(%p), metadata={op_name="jit(f)/moe_aux/tanh"}
}
"""


def test_moe_weights_split_mixed_fusion():
    """A fusion whose interior is 25% router bytes (32 of 128) charges the
    router exactly that fraction; the untagged remainder is unassigned.
    Tagged non-fusion ops keep weight 1.0. The winner-take-all map
    (build_op_moe_tags) would have charged this fusion 100% to the router
    — the r7 mega-fusion misattribution this model corrects."""
    import profile_step as ps

    w = ps.build_op_moe_weights(SYNTH_HLO)
    assert w["fusion.1"] == {"moe_router": 32.0 / 128.0}
    assert w["t"] == {"moe_aux": 1.0}
    # the interior tagged line is itself weighted (its own op_bytes exist)
    assert w["a.1"] == {"moe_router": 1.0}
    # contrast: the line-majority map attributes the whole fusion
    tags = ps.build_op_moe_tags(SYNTH_HLO)
    assert tags["fusion.1"] == "moe_router"
