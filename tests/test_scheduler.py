"""Multi-tenant fleet scheduler (ISSUE r16): placement, preemption, backfill.

The pure layer (utils/scheduler.py) is driven directly — every decision is a
function of job states and a caller-supplied clock, so the priority /
preemption / backoff / backfill semantics are tested without spawning
anything. The fleet e2e test runs the real ``launch.py --fleet`` control
loop against jax-free stub jobs (the test_elastic.py supervisor style); the
drill with the *real* trainer lives in the dryrun gauntlet
(__graft_entry__.py leg 16).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from pytorch_distributed_training_example_tpu.utils import elastic
from pytorch_distributed_training_example_tpu.utils import fleetobs
from pytorch_distributed_training_example_tpu.utils import (
    scheduler as scheduler_lib)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check_regression(*argv):
    spec = importlib.util.spec_from_file_location(
        "check_regression_under_test",
        os.path.join(REPO, "benchmarks", "check_regression.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main(list(argv))


def _spec(name, ckdir=None, **kw):
    cmd = ["job.py"]
    if ckdir is not None:
        cmd += ["--checkpoint-dir", str(ckdir)]
    return scheduler_lib.JobSpec(name=name, cmd=tuple(cmd), **kw)


# ---------------------------------------------------------------------------
# parse_world / load_jobs
# ---------------------------------------------------------------------------


def test_parse_world_grammar():
    assert scheduler_lib.parse_world("2") == (2, 1 << 30)
    assert scheduler_lib.parse_world("1:4") == (1, 4)
    for junk in ("0", "4:2", "0:3"):
        with pytest.raises(ValueError):
            scheduler_lib.parse_world(junk)


def test_load_jobs_parses_and_validates(tmp_path):
    path = tmp_path / "jobs.json"
    path.write_text(json.dumps({"pool": 4, "jobs": [
        {"name": "a", "cmd": ["main.py", "--checkpoint-dir", "/ck/a"],
         "world": "1:2", "priority": 5, "backoff_s": 0.5,
         "env": {"FOO": "1"}},
        {"name": "b", "cmd": ["main.py"], "after": "a",
         "after_event": "checkpoint"},
    ]}))
    pool, specs = scheduler_lib.load_jobs(str(path))
    assert pool == 4
    a, b = specs
    assert (a.min_world, a.max_world, a.priority) == (1, 2, 5)
    assert a.checkpoint_dir == "/ck/a"
    assert a.env == (("FOO", "1"),)
    assert b.after == "a" and b.after_event == "checkpoint"
    assert b.checkpoint_dir is None

    for bad in (
        {"pool": 0, "jobs": [{"name": "a", "cmd": ["x"]}]},
        {"pool": 2, "jobs": []},
        {"pool": 2, "jobs": [{"name": "a", "cmd": []}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"]},
                             {"name": "a", "cmd": ["x"]}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"], "world": "3"}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"], "after": "ghost"}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"], "after": "a"}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"],
                              "after_event": "vibes"}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"], "kind": "batch"}]},
    ):
        path.write_text(json.dumps(bad))
        with pytest.raises(ValueError):
            scheduler_lib.load_jobs(str(path))


def test_load_jobs_parses_serve_kind(tmp_path):
    path = tmp_path / "jobs.json"
    path.write_text(json.dumps({"pool": 2, "jobs": [
        {"name": "train", "cmd": ["main.py"]},
        {"name": "api", "cmd": ["main.py", "--serve"], "kind": "serve"},
    ]}))
    _, specs = scheduler_lib.load_jobs(str(path))
    assert [s.kind for s in specs] == ["train", "serve"]
    sched = scheduler_lib.FleetScheduler(2, specs)
    assert sched.gauges()["fleet_jobs_serve"] == 1


# ---------------------------------------------------------------------------
# plan(): tiers, surplus, caps, claims
# ---------------------------------------------------------------------------


def test_single_job_gets_min_plus_surplus_to_cap():
    sched = scheduler_lib.FleetScheduler(4, [_spec("a", min_world=1,
                                                  max_world=3)])
    (d,) = sched.plan(0.0)
    assert (d["action"], d["job"], d["world"]) == ("launch", "a", 3)
    assert sched.free() == 1  # MAX capped below the pool


def test_priority_tier_grows_before_lower_tier_sees_devices():
    sched = scheduler_lib.FleetScheduler(4, [
        _spec("lo", priority=0, min_world=1, max_world=4),
        _spec("hi", priority=1, min_world=1, max_world=3),
    ])
    ds = sched.plan(0.0)
    worlds = {d["job"]: d["world"] for d in ds if d["action"] == "launch"}
    # hi takes its cap first; lo backfills what is left.
    assert worlds == {"hi": 3, "lo": 1}


def test_surplus_within_tier_is_goodput_weighted():
    sched = scheduler_lib.FleetScheduler(8, [
        _spec("a", min_world=1), _spec("b", min_world=1),
    ])
    sched.state("a").weight = 0.9
    sched.state("b").weight = 0.3
    ds = sched.plan(0.0)
    worlds = {d["job"]: d["world"] for d in ds}
    # D'Hondt over 6 surplus seats at weights 0.9 vs 0.3: quotients give
    # the productive job 5 of the 6 (plus its min).
    assert worlds["a"] + worlds["b"] == 8
    assert worlds["a"] > worlds["b"]
    assert worlds == {"a": 6, "b": 2}


def test_equal_weights_split_surplus_evenly_name_tiebreak():
    sched = scheduler_lib.FleetScheduler(5, [
        _spec("a", min_world=1), _spec("b", min_world=1),
    ])
    ds = sched.plan(0.0)
    worlds = {d["job"]: d["world"] for d in ds}
    assert worlds == {"a": 3, "b": 2}  # odd seat goes to the earlier name


def test_dead_hosts_cap_allocation_and_returns_restore_it(tmp_path):
    ck = tmp_path / "ck"
    ck.mkdir()
    sched = scheduler_lib.FleetScheduler(
        4, [_spec("a", ckdir=ck, min_world=2, max_world=4)])
    elastic.record_dead_host(str(ck), 3, reason="probe")
    (d,) = sched.plan(0.0)
    assert d["world"] == 3  # 4 minus one currently-dead host
    sched.on_exit("a", 0, 1.0)

    # Below MIN the job is unplaceable; a host return reopens the range.
    sched = scheduler_lib.FleetScheduler(
        4, [_spec("b", ckdir=ck, min_world=4, max_world=4)])
    assert sched.plan(0.0) == []
    assert sched.state("b").status == scheduler_lib.PENDING
    elastic.record_host_return(str(ck), 3, reason="repaired")
    (d,) = sched.plan(1.0)
    assert d["world"] == 4


def test_preemption_evicts_cheapest_strictly_lower_tier():
    sched = scheduler_lib.FleetScheduler(4, [
        _spec("a", priority=0, min_world=2, max_world=2),
        _spec("b", priority=1, min_world=2, max_world=2),
        # Arrival gated on a's start so the first pass fills the pool with
        # the low tiers before the big job shows up.
        _spec("c", priority=5, min_world=3, after="a"),
    ])
    ds = sched.plan(0.0)
    assert {d["job"] for d in ds if d["action"] == "launch"} == {"a", "b"}
    # c arrives needing 3; preemption picks the LOWEST tier first (a) and
    # keeps evicting upward until the shortfall is covered.
    ds = sched.plan(1.0)
    preempts = [d for d in ds if d["action"] == "preempt"]
    assert [d["job"] for d in preempts] == ["a", "b"]  # needs 3, frees 2+2
    assert sched.state("a").status == scheduler_lib.PREEMPTING
    # While victims are dying, no double-preemption on the next pass.
    assert sched.plan(2.0) == []
    sched.on_exit("a", 75, 3.0)
    sched.on_exit("b", 75, 3.0)
    (d,) = sched.plan(4.0)
    assert (d["job"], d["world"]) == ("c", 4)
    # Equal tier never preempts itself: a cannot evict b back.
    assert all(x["action"] != "preempt" for x in sched.plan(5.0))


def test_scheduler_preemption_requeues_without_budget_burn():
    sched = scheduler_lib.FleetScheduler(2, [
        _spec("lo", priority=0),
        _spec("hi", priority=9, min_world=2, after="lo")])
    sched.plan(0.0)  # lo takes the pool; hi hasn't arrived yet
    sched.plan(1.0)  # hi preempts lo
    row = sched.on_exit("lo", 75, 2.0)
    st = sched.state("lo")
    assert st.status == scheduler_lib.PENDING
    assert st.restarts == 0
    assert "no budget burned" in row["reason"]


def test_failure_backoff_doubles_then_budget_exhausts():
    sched = scheduler_lib.FleetScheduler(
        2, [_spec("a", max_restarts=2, backoff_s=1.0)])
    sched.plan(0.0)
    row = sched.on_exit("a", 76, 10.0)
    st = sched.state("a")
    assert st.status == scheduler_lib.BACKOFF
    assert st.next_eligible_s == 11.0 and "restart 1/2" in row["reason"]
    assert sched.plan(10.5) == []  # timer not expired
    sched.plan(11.5)
    assert st.status == scheduler_lib.RUNNING
    row = sched.on_exit("a", 1, 20.0)
    assert st.next_eligible_s == 22.0  # doubled
    sched.plan(22.5)
    row = sched.on_exit("a", 1, 30.0)
    assert st.status == scheduler_lib.FAILED
    assert row["action"] == "giveup" and "exhausted" in row["reason"]
    assert sched.finished()


def test_backoff_claim_blocks_lower_tier_from_squatting():
    sched = scheduler_lib.FleetScheduler(3, [
        _spec("lo", priority=0, min_world=1, max_world=3, backoff_s=5.0),
        _spec("hi", priority=9, min_world=2, max_world=3, backoff_s=5.0),
    ])
    sched.plan(0.0)  # hi 3, lo starved
    assert sched.state("hi").world == 3
    sched.on_exit("hi", 76, 1.0)  # backoff until 6.0
    (d,) = sched.plan(2.0)
    # lo backfills ONLY what hi's claim leaves over: 3 - min(2, cap) = 1.
    assert (d["job"], d["world"]) == ("lo", 1)
    sched.plan(7.0)
    assert sched.state("hi").world == 2  # relaunched inside its claim


def test_dependency_gates_eligibility(tmp_path):
    ck = tmp_path / "dep_ck"
    ck.mkdir()
    sched = scheduler_lib.FleetScheduler(2, [
        _spec("a", ckdir=ck, max_world=1),
        _spec("b", after="a", after_event="checkpoint"),
        _spec("c", after="a"),  # after_event=start
    ])
    ds = sched.plan(0.0)
    assert {d["job"] for d in ds} == {"a"}  # b, c both gated
    ds = sched.plan(1.0)
    assert {d["job"] for d in ds} == {"c"}  # a started; b needs a checkpoint
    (ck / "step_00000004").mkdir()
    sched.on_exit("c", 0, 2.0)
    ds = sched.plan(3.0)
    assert {d["job"] for d in ds} == {"b"}


def test_mark_starved_and_gauges():
    sched = scheduler_lib.FleetScheduler(2, [
        _spec("a"), _spec("b", after="a", after_event="checkpoint")])
    sched.plan(0.0)
    sched.on_exit("a", 0, 1.0)  # done, never checkpointed -> b is stuck
    assert sched.plan(2.0) == []
    g = sched.gauges()
    assert g["fleet_pool_devices"] == 2 and g["fleet_jobs_pending"] == 1
    assert g["fleet_job_world_a"] == 0
    rows = sched.mark_starved()
    assert [r["job"] for r in rows] == ["b"]
    assert sched.finished()
    assert sched.gauges()["fleet_jobs_failed"] == 1


def test_placement_log_is_deterministic_and_timestamp_free(tmp_path):
    def drill(log_dir):
        os.makedirs(log_dir, exist_ok=True)
        sched = scheduler_lib.FleetScheduler(3, [
            _spec("lo", priority=0, max_world=2, backoff_s=1.0),
            _spec("hi", priority=9, min_world=2, max_world=3,
                  backoff_s=1.0, after="lo"),
        ], log_dir=log_dir)
        sched.plan(0.0)          # lo -> 2
        sched.plan(1.0)          # hi preempts lo
        sched.on_exit("lo", 75, 2.0)
        sched.plan(3.0)          # hi -> 3
        sched.on_exit("hi", 76, 4.0)
        sched.plan(4.5)          # lo backfills at 1 under hi's claim
        sched.plan(6.0)          # hi relaunches at its claim
        sched.on_exit("lo", 0, 7.0)
        sched.on_exit("hi", 0, 8.0)
        return open(os.path.join(log_dir,
                                 scheduler_lib.PLACEMENT_FILE)).read()

    a = drill(str(tmp_path / "run_a"))
    b = drill(str(tmp_path / "run_b"))
    assert a == b
    rows = [json.loads(line) for line in a.splitlines()]
    assert [r["seq"] for r in rows] == list(range(1, len(rows) + 1))
    assert all(set(r) == {"seq", "action", "job", "world", "free", "reason"}
               for r in rows)  # no timestamps, ever
    assert [r["action"] for r in rows] == [
        "launch", "preempt", "exit", "launch", "exit", "launch", "launch",
        "done", "done"]


def test_quantize_weight_floors_and_damps():
    assert scheduler_lib.quantize_weight(0.93) == 0.9
    assert scheduler_lib.quantize_weight(0.88) == 0.9
    assert scheduler_lib.quantize_weight(0.0) == 0.1
    assert scheduler_lib.quantize_weight(-1.0) == 0.1


# ---------------------------------------------------------------------------
# cluster goodput aggregation + gate
# ---------------------------------------------------------------------------


def _job_goodput(run_id, wall, step_s, restart_s=0.0, attempts=1):
    cov = (step_s + restart_s) / wall
    return {"run_id": run_id, "wall_s": wall,
            "categories_s": {"step": step_s, "restart": restart_s},
            "counts": {"step": 10}, "coverage": round(cov, 4),
            "goodput_fraction": round(step_s / wall, 4),
            "attempts": attempts}


def test_aggregate_cluster_goodput_sums_and_keeps_run_ids():
    agg = fleetobs.aggregate_cluster_goodput({
        "hi": _job_goodput("run-hi", 10.0, 9.0, restart_s=0.8, attempts=2),
        "lo": _job_goodput("run-lo", 5.0, 4.8),
    })
    assert agg["cluster"] is True
    assert agg["jobs"] == ["hi", "lo"]
    assert sorted(agg["run_ids"]) == ["run-hi", "run-lo"]
    assert agg["wall_s"] == 15.0
    assert agg["categories_s"]["step"] == 13.8
    assert agg["goodput_fraction"] == round(13.8 / 15.0, 4)
    assert agg["coverage"] == round(14.6 / 15.0, 4)
    assert agg["attempts"] == 3
    assert agg["per_job"]["lo"]["run_id"] == "run-lo"
    assert fleetobs.aggregate_cluster_goodput({}) == {}


def test_cluster_goodput_gate_accepts_distinct_run_ids(tmp_path, capsys):
    agg = fleetobs.aggregate_cluster_goodput({
        "hi": _job_goodput("run-hi", 10.0, 9.0, restart_s=0.8),
        "lo": _job_goodput("run-lo", 5.0, 4.8),
    })
    path = tmp_path / "cluster_goodput.json"
    path.write_text(json.dumps(agg))
    # Without --cluster the distinct run_ids trip the mixed-run refusal...
    assert _check_regression("--goodput", str(path)) == 1
    assert "MIXED-RUN" in capsys.readouterr().out
    # ...with it, they are the expected multi-tenant shape.
    assert _check_regression("--goodput", str(path), "--cluster") == 0
    out = capsys.readouterr().out
    assert "OK cluster goodput" in out and "2 job(s)" in out


def test_cluster_goodput_gate_still_enforces_coverage(tmp_path, capsys):
    bad = fleetobs.aggregate_cluster_goodput(
        {"a": _job_goodput("run-a", 10.0, 5.0)})
    path = tmp_path / "cluster_goodput.json"
    path.write_text(json.dumps(bad))
    assert _check_regression("--goodput", str(path), "--cluster") == 1
    assert "REGRESSION cluster goodput" in capsys.readouterr().out
    # And a single-run file is rejected under --cluster (wrong schema).
    path.write_text(json.dumps(_job_goodput("run-a", 10.0, 9.9)))
    assert _check_regression("--goodput", str(path), "--cluster") == 1
    assert "MALFORMED" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# fleet e2e: the real launch.py --fleet loop over jax-free stub jobs
# ---------------------------------------------------------------------------


_STUB_JOB = """\
import json, os, signal, sys, time
args = sys.argv[1:]
ckdir = args[args.index('--checkpoint-dir') + 1]
name = args[args.index('--name') + 1]
os.makedirs(ckdir, exist_ok=True)
world = 0
for tok in os.environ.get('XLA_FLAGS', '').split():
    if 'device_count=' in tok:
        world = int(tok.split('=')[1])

def write_goodput():
    with open(os.path.join(ckdir, 'goodput.json'), 'w') as fh:
        json.dump({'run_id': 'run-' + name, 'wall_s': 1.0,
                   'coverage': 0.97, 'goodput_fraction': 0.9,
                   'categories_s': {'step': 0.9, 'restart': 0.07},
                   'counts': {'step': 10},
                   'attempts': 1 + ('--resume' in args)}, fh)

def on_term(signum, frame):
    # The emergency-checkpoint-and-yield path, stubbed.
    os.makedirs(os.path.join(ckdir, 'step_00000001'), exist_ok=True)
    write_goodput()
    with open(os.path.join(ckdir, 'preempted.txt'), 'a') as fh:
        fh.write('world=%d\\n' % world)
    os._exit(75)

signal.signal(signal.SIGTERM, on_term)
if '--resume' in args:
    with open(os.path.join(ckdir, 'resumed.txt'), 'w') as fh:
        fh.write('world=%d' % world)
    write_goodput()
    sys.exit(0)
os.makedirs(os.path.join(ckdir, 'step_00000001'), exist_ok=True)
if '--short' in args:
    time.sleep(0.3)
    write_goodput()
    sys.exit(0)
time.sleep(60)
sys.exit(1)
"""


def _run_fleet(tmp_path, tag):
    work = tmp_path / tag
    work.mkdir()
    stub = work / "stub_job.py"
    stub.write_text(_STUB_JOB)
    lo_ck, hi_ck = work / "ck_lo", work / "ck_hi"
    jobs = work / "jobs.json"
    jobs.write_text(json.dumps({"pool": 3, "jobs": [
        {"name": "lo", "priority": 0, "world": "1:2", "backoff_s": 0.1,
         "cmd": [str(stub), "--name", "lo",
                 "--checkpoint-dir", str(lo_ck)]},
        {"name": "hi", "priority": 10, "world": "2:3", "backoff_s": 0.1,
         "after": "lo", "after_event": "checkpoint",
         "cmd": [str(stub), "--name", "hi", "--short",
                 "--checkpoint-dir", str(hi_ck)]},
    ]}))
    res = subprocess.run(
        [sys.executable, "launch.py", "--fleet", str(jobs),
         "--log-dir", str(work), "--fleet-poll", "0.05"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    return res, work, lo_ck, hi_ck


def test_fleet_preempts_backfills_and_aggregates_goodput(tmp_path):
    res, work, lo_ck, hi_ck = _run_fleet(tmp_path, "run_a")
    assert res.returncode == 0, res.stderr
    err = res.stderr
    # lo launched wide, was preempted for hi, and resumed afterwards.
    assert "launch lo at world 2 (attempt 1)" in err, err
    assert "preempt lo" in err and "priority 10 > 0" in err, err
    assert "launch hi at world 3 (attempt 1)" in err, err
    assert "launch lo at world 2 (attempt 2)" in err, err
    assert (lo_ck / "preempted.txt").read_text() == "world=2\n"
    assert (lo_ck / "resumed.txt").read_text() == "world=2"
    # Decision order in the placement log: preempt strictly before hi runs.
    rows = [json.loads(line) for line in
            (work / "placement.jsonl").read_text().splitlines()]
    actions = [(r["action"], r["job"]) for r in rows]
    assert actions.index(("preempt", "lo")) < actions.index(("launch", "hi"))
    assert ("done", "hi") in actions and ("done", "lo") in actions
    # Cluster aggregation: one summary, both jobs, distinct run ids, gated.
    agg = json.loads((work / "cluster_goodput.json").read_text())
    assert agg["jobs"] == ["hi", "lo"]
    assert sorted(agg["run_ids"]) == ["run-hi", "run-lo"]
    assert _check_regression("--goodput", str(work / "cluster_goodput.json"),
                             "--cluster") == 0

    # Same fleet, second run: the decision stream is event-chained, so the
    # placement log is byte-identical (the determinism contract).
    res_b, work_b, _, _ = _run_fleet(tmp_path, "run_b")
    assert res_b.returncode == 0, res_b.stderr
    assert ((work / "placement.jsonl").read_text()
            == (work_b / "placement.jsonl").read_text())


def test_fleet_starved_job_fails_the_fleet(tmp_path):
    work = tmp_path / "starved"
    work.mkdir()
    stub = work / "stub_job.py"
    stub.write_text(_STUB_JOB)
    jobs = work / "jobs.json"
    jobs.write_text(json.dumps({"pool": 2, "jobs": [
        {"name": "a", "world": "1", "cmd": [
            str(stub), "--name", "a", "--short",
            "--checkpoint-dir", str(work / "ck_a")]},
        # b waits for a checkpoint a never... a DOES write one; gate b on a
        # job that never starts instead: depend on itself via a dead range.
        {"name": "b", "world": "2:2", "max_restarts": 0, "cmd": [
            str(stub), "--name", "b", "--short",
            "--checkpoint-dir", str(work / "ck_b")]},
    ]}))
    # Pin b's range shut before the fleet starts: 2 dead hosts -> cap 0.
    (work / "ck_b").mkdir()
    elastic.record_dead_host(str(work / "ck_b"), 0, reason="pinned")
    elastic.record_dead_host(str(work / "ck_b"), 1, reason="pinned")
    res = subprocess.run(
        [sys.executable, "launch.py", "--fleet", str(jobs),
         "--log-dir", str(work), "--fleet-poll", "0.05"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert res.returncode == 1, res.stderr
    assert "give up on b" in res.stderr, res.stderr
    assert "'a': 'done'" in res.stderr and "'b': 'failed'" in res.stderr


# ---------------------------------------------------------------------------
# straggler-fed eviction (evict_after / evict_decay)
# ---------------------------------------------------------------------------


def _flag(ckdir, step, rank, flagged=True):
    fleetobs.append_straggler_flag(str(ckdir), {
        "step": step, "slowest_rank": rank, "delta_s": 0.25,
        "cause": "input_wait_s", "flagged": flagged, "source": "live"})


def test_load_jobs_parses_and_validates_evict_knobs(tmp_path):
    path = tmp_path / "jobs.json"
    path.write_text(json.dumps({"pool": 4, "jobs": [
        {"name": "a", "cmd": ["main.py"], "evict_after": 3,
         "evict_decay": 5},
        {"name": "b", "cmd": ["main.py"]},
    ]}))
    _, (a, b) = scheduler_lib.load_jobs(str(path))
    assert (a.evict_after, a.evict_decay) == (3, 5)
    assert (b.evict_after, b.evict_decay) == (0, 8)  # disabled by default
    for bad in (
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"],
                              "evict_after": -1}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"],
                              "evict_decay": 0}]},
        {"pool": 2, "jobs": [{"name": "a", "cmd": ["x"], "kind": "serve",
                              "evict_after": 2}]},
    ):
        path.write_text(json.dumps(bad))
        with pytest.raises(ValueError):
            scheduler_lib.load_jobs(str(path))


def test_straggler_eviction_preempts_marks_dead_and_backfills(tmp_path):
    ck = tmp_path / "ck_a"
    ck.mkdir()
    sched = scheduler_lib.FleetScheduler(3, [
        _spec("a", ckdir=ck, min_world=1, max_world=2, evict_after=3),
        _spec("b", min_world=1, max_world=1),
    ], log_dir=str(tmp_path))
    sched.plan(0.0)
    for s in range(3):
        _flag(ck, s, 1)
    (d,) = sched.plan(1.0)
    assert d["action"] == "preempt" and d["job"] == "a"
    # The reason quotes CONFIG (the threshold), never the observed streak —
    # byte-determinism of placement.jsonl across same-seed drills.
    assert "flagged 3 consecutive windows" in d["reason"]
    assert elastic.effective_dead_hosts(str(ck)) == {1}
    st = sched.state("a")
    assert st.status == scheduler_lib.PREEMPTING
    # Graceful exit: requeued, restart budget untouched.
    row = sched.on_exit("a", 75, 2.0)
    assert "no budget burned" in row["reason"] and st.restarts == 0
    (d,) = sched.plan(3.0)
    assert (d["action"], d["job"], d["world"]) == ("launch", "a", 1)


def test_straggler_eviction_requires_fresh_evidence(tmp_path):
    ck = tmp_path / "ck_a"
    ck.mkdir()
    sched = scheduler_lib.FleetScheduler(2, [
        _spec("a", ckdir=ck, min_world=1, max_world=2, evict_after=2)])
    sched.plan(0.0)
    _flag(ck, 0, 1), _flag(ck, 1, 1)
    (d,) = sched.plan(1.0)
    assert d["action"] == "preempt"
    sched.on_exit("a", 75, 2.0)
    sched.plan(3.0)  # relaunch
    # The old flag rows are still on disk; without NEW rows the job must
    # never be evicted again.
    assert sched.plan(4.0) == []
    _flag(ck, 9, 1), _flag(ck, 10, 1)
    (d,) = sched.plan(5.0)
    assert d["action"] == "preempt"


def test_straggler_eviction_never_shrinks_below_min_world(tmp_path):
    ck = tmp_path / "ck_a"
    ck.mkdir()
    sched = scheduler_lib.FleetScheduler(2, [
        _spec("a", ckdir=ck, min_world=2, max_world=2, evict_after=2)])
    sched.plan(0.0)
    _flag(ck, 0, 1), _flag(ck, 1, 1)
    assert sched.plan(1.0) == []  # evicting would leave cap 1 < min 2
    assert elastic.effective_dead_hosts(str(ck)) == set()
    assert sched.state("a").status == scheduler_lib.RUNNING


def test_straggler_suspicion_decays_and_readmits(tmp_path):
    ck = tmp_path / "ck_a"
    ck.mkdir()
    sched = scheduler_lib.FleetScheduler(3, [
        _spec("a", ckdir=ck, min_world=1, max_world=2, evict_after=2,
              evict_decay=3),
        _spec("b", min_world=1, max_world=1, max_restarts=9),
    ], log_dir=str(tmp_path))
    sched.plan(0.0)                      # seq 1,2: launches
    _flag(ck, 0, 1), _flag(ck, 1, 1)
    sched.plan(1.0)                      # seq 3: preempt a, host 1 dead
    sched.on_exit("a", 75, 2.0)          # seq 4
    sched.plan(3.0)                      # seq 5: a backfills at world 1
    sched.on_exit("b", 1, 4.0)           # seq 6: b fails -> backoff
    ds = sched.plan(100.0)               # decay due (6 - 3 >= 3)
    assert [d["action"] for d in ds] == ["readmit", "launch"]
    assert "suspicion decayed after 3 decisions" in ds[0]["reason"]
    assert elastic.effective_dead_hosts(str(ck)) == set()
    assert sched.state("a").suspects == []


def test_straggler_eviction_decisions_are_seq_based_not_clocked(tmp_path):
    # Identical scripted histories -> byte-identical placement logs, no
    # matter what wall-clock values drive the passes.
    def drill(log_dir, times):
        ck = os.path.join(log_dir, "ck_a")
        os.makedirs(ck)
        sched = scheduler_lib.FleetScheduler(3, [
            _spec("a", ckdir=ck, min_world=1, max_world=2, evict_after=2,
                  evict_decay=2),
            _spec("b", min_world=1, max_world=1),
        ], log_dir=log_dir)
        sched.plan(times[0])
        _flag(ck, 0, 1), _flag(ck, 1, 1)
        sched.plan(times[1])
        sched.on_exit("a", 75, times[2])
        sched.plan(times[3])
        sched.on_exit("b", 0, times[4])
        sched.plan(times[5])
        sched.on_exit("a", 0, times[6])
        return open(os.path.join(log_dir,
                                 scheduler_lib.PLACEMENT_FILE)).read()

    a = drill(str(tmp_path / "a"), [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    b = drill(str(tmp_path / "b"), [10.0, 40.0, 41.5, 90.0, 91.0, 500.0,
                                    501.0])
    assert a == b
    rows = [json.loads(line) for line in a.splitlines()]
    assert all(set(r) == {"seq", "action", "job", "world", "free", "reason"}
               for r in rows)
    assert "readmit" in [r["action"] for r in rows]


def test_straggler_eviction_respects_backoff_claims(tmp_path):
    # An evicted job requeues into the normal placement flow: a higher-
    # priority job waiting out a backoff keeps its claim, so the evicted
    # job's relaunch cannot squat on the claimant's minimum.
    ck = tmp_path / "ck_lo"
    ck.mkdir()
    sched = scheduler_lib.FleetScheduler(2, [
        _spec("lo", ckdir=ck, priority=0, min_world=2, max_world=2,
              evict_after=2),
        _spec("hi", priority=9, min_world=2, max_world=2, backoff_s=50.0),
    ], log_dir=str(tmp_path))
    sched.plan(0.0)                     # hi takes the pool
    sched.on_exit("hi", 1, 1.0)         # hi -> backoff until 51.0
    sched.plan(2.0)                     # lo launches at 2 meanwhile?
    # lo cannot launch under hi's claim (claim = hi's min 2 = whole pool).
    assert sched.state("lo").status == scheduler_lib.PENDING
    sched.plan(51.0)                    # hi relaunches
    assert sched.state("hi").status == scheduler_lib.RUNNING
    sched.on_exit("hi", 0, 52.0)
    sched.plan(53.0)                    # lo finally launches at 2
    assert sched.state("lo").status == scheduler_lib.RUNNING
    _flag(ck, 0, 1), _flag(ck, 1, 1)
    # min_world 2 and pool 2: eviction would pin lo below its minimum.
    assert sched.plan(54.0) == []
    assert sched.state("lo").status == scheduler_lib.RUNNING
