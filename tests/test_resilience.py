"""Fault-tolerance layer (ISSUE r9): resilience, chaos, integrity, supervisor.

Covers the acceptance scenarios end-to-end:
- SIGTERM mid-epoch -> committed emergency checkpoint + distinct exit code
- CRC-corrupted / truncated / manifest-less latest checkpoint -> fallback
  restore of the previous committed step
- injected nan_grad with --anomaly-action rollback -> restores and continues
  sample-exact (index log identical to an uninterrupted run)
- injected checkpoint io errors -> retriable_io retries, then succeeds
- chaos specs are deterministic for a given (spec, seed)

In-process tests exercise the modules directly; the subprocess tests run the
real ``main.py`` CLI (and the ``launch.py`` supervisor restart loop) exactly
as an operator would.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import (
    checkpoint as ckpt_lib, mesh as mesh_lib, optim, train_loop)
from pytorch_distributed_training_example_tpu.data.loader import INDEX_LOG_ENV
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import (
    sharding as sharding_lib)
from pytorch_distributed_training_example_tpu.utils import (
    chaos as chaos_lib, resilience, watchdog)
from pytorch_distributed_training_example_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPE = 5  # steps per epoch in the subprocess drills


# ---------------------------------------------------------------------------
# resilience: signal flag + retriable io
# ---------------------------------------------------------------------------


def test_signal_sets_flag_without_exiting():
    assert resilience.install()
    try:
        assert not resilience.preempted()
        os.kill(os.getpid(), signal.SIGTERM)  # real delivery path
        deadline = time.monotonic() + 5
        while not resilience.preempted() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert resilience.preempted()
        assert resilience.preempt_signal() == signal.SIGTERM
    finally:
        resilience.uninstall()
        resilience.reset()
    assert not resilience.preempted()


def test_install_off_main_thread_is_refused():
    result = {}
    t = threading.Thread(target=lambda: result.update(
        ok=resilience.install()))
    t.start()
    t.join()
    assert result["ok"] is False
    assert not resilience.preempted()


def test_retriable_io_retries_transient_oserror():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "done"

    assert resilience.retriable_io(flaky, _base_delay_s=0.001) == "done"
    assert len(calls) == 3


def test_retriable_io_bounded_and_reraises():
    calls = []

    def broken():
        calls.append(1)
        raise OSError("persistent")

    with pytest.raises(OSError, match="persistent"):
        resilience.retriable_io(broken, _attempts=3, _base_delay_s=0.001)
    assert len(calls) == 3


def test_fault_hook_feeds_the_retry_path():
    state = {"faults": 2, "ran": 0}

    def hook(what):
        if state["faults"] > 0:
            state["faults"] -= 1
            raise OSError(f"injected [{what}]")

    resilience.set_fault_hook(hook)
    try:
        def op():
            state["ran"] += 1
            return 42
        assert resilience.retriable_io(op, _base_delay_s=0.001) == 42
    finally:
        resilience.set_fault_hook(None)
    assert state["ran"] == 1  # the two faults fired BEFORE the op ran


# ---------------------------------------------------------------------------
# chaos spec parsing + determinism
# ---------------------------------------------------------------------------


def test_parse_spec():
    evs = chaos_lib.parse_spec("sigterm@step=7, nan_grad@step=5,truncate_ckpt")
    assert [(e.name, e.key, e.value) for e in evs] == [
        ("sigterm", "step", 7), ("nan_grad", "step", 5),
        ("truncate_ckpt", "save", 1)]
    for junk in ("frobnicate@step=1", "sigterm", "sigterm@save=1",
                 "sigterm@step=x", ",", ""):
        with pytest.raises(ValueError):
            chaos_lib.parse_spec(junk)


def test_chaos_nan_grad_poisons_floats_only_and_logs(tmp_path, monkeypatch):
    monkeypatch.setattr(chaos_lib.ChaosEngine, "STALL_S", 0.01)
    spec, seed = "nan_grad@step=2,loader_stall@batch=4", 3

    def drive(log_dir):
        eng = chaos_lib.ChaosEngine(spec, seed=seed, log_dir=str(log_dir))
        eng.steps_per_epoch = SPE
        for g in range(6):
            batch = {"image": np.ones((2, 4), np.float32),
                     "label": np.arange(2, dtype=np.int32)}
            out = eng.batch_hook(g // SPE, g % SPE, batch)
            if g == 2:
                assert np.isnan(out["image"]).all()
                assert (out["label"] == batch["label"]).all()  # ints intact
                assert not np.isnan(batch["image"]).any()  # input not mutated
            else:
                assert out is batch
        return (log_dir / chaos_lib.CHAOS_LOG).read_text()

    d1, d2 = tmp_path / "a", tmp_path / "b"
    d1.mkdir(), d2.mkdir()
    log1, log2 = drive(d1), drive(d2)
    assert log1 == log2  # same spec + seed -> byte-identical injection log
    rows = [json.loads(line) for line in log1.splitlines()]
    assert {r["event"] for r in rows} == {"nan_grad", "loader_stall"}
    assert all(r["seed"] == seed for r in rows)


def test_chaos_events_fire_once():
    eng = chaos_lib.ChaosEngine("nan_grad@step=1", seed=0)
    eng.steps_per_epoch = SPE
    batch = {"x": np.ones(3, np.float32)}
    assert np.isnan(eng.batch_hook(0, 1, batch)["x"]).all()
    assert eng.batch_hook(0, 1, batch) is batch  # resumed run: no re-trip


# ---------------------------------------------------------------------------
# checkpoint integrity: CRC fallback, manifest tolerance, wait() re-raise
# ---------------------------------------------------------------------------


def _state(mesh, seed=0):
    bundle = registry.create_model("resnet_micro", num_classes=10,
                                   image_size=32, dtype=jnp.float32,
                                   param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(Config(), steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    return train_loop.create_train_state(bundle.module, tx,
                                         bundle.input_template, mesh, rules,
                                         seed=seed)


def _two_saves(tmp_path, devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    ck = ckpt_lib.Checkpointer(str(tmp_path))
    s1, s2 = _state(mesh, seed=1), _state(mesh, seed=2)
    ck.save(s1, 1, extra={"tag": 1}, block=True)
    ck.save(s2, 2, extra={"tag": 2}, block=True)
    return ck, mesh, s1


def _first_array_file(tmp_path, step):
    arrays = os.path.join(str(tmp_path), f"step_{step:08d}", "arrays")
    return os.path.join(arrays, sorted(os.listdir(arrays))[0])


def test_crc_bitflip_falls_back_to_previous_step(tmp_path, devices):
    ck, mesh, s1 = _two_saves(tmp_path, devices)
    target = _first_array_file(tmp_path, 2)
    with open(target, "r+b") as fh:
        fh.seek(-1, os.SEEK_END)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_END)
        fh.write(bytes([b[0] ^ 0xFF]))

    restored, extra = ck.restore(_state(mesh, seed=9))
    assert ck.last_restored_step == 1 and extra == {"tag": 1}
    for x, y in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(s1.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    with pytest.raises(ckpt_lib.CheckpointCorruptError, match="CRC mismatch"):
        ck.restore(_state(mesh, seed=9), step=2)


def test_truncated_file_falls_back(tmp_path, devices):
    ck, mesh, _ = _two_saves(tmp_path, devices)
    target = _first_array_file(tmp_path, 2)
    with open(target, "r+b") as fh:
        fh.truncate(max(os.path.getsize(target) // 2, 1))
    ck.restore(_state(mesh, seed=9))
    assert ck.last_restored_step == 1


def test_missing_manifest_falls_back(tmp_path, devices):
    ck, mesh, _ = _two_saves(tmp_path, devices)
    os.remove(os.path.join(str(tmp_path), "step_00000002",
                           ckpt_lib.MANIFEST_FILE))
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 1
    ck.restore(_state(mesh, seed=9))
    assert ck.last_restored_step == 1


def test_garbage_manifest_falls_back(tmp_path, devices):
    ck, mesh, _ = _two_saves(tmp_path, devices)
    with open(os.path.join(str(tmp_path), "step_00000002",
                           ckpt_lib.MANIFEST_FILE), "w") as fh:
        fh.write("{not json")
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 1
    ck.restore(_state(mesh, seed=9))
    assert ck.last_restored_step == 1


def test_all_checkpoints_corrupt_raises(tmp_path, devices):
    ck, mesh, _ = _two_saves(tmp_path, devices)
    for step in (1, 2):
        target = _first_array_file(tmp_path, step)
        with open(target, "r+b") as fh:
            fh.truncate(1)
    with pytest.raises(ckpt_lib.CheckpointCorruptError,
                       match="every committed checkpoint"):
        ck.restore(_state(mesh, seed=9))


def test_quarantine_hides_step_from_discovery(tmp_path, devices):
    ck, _, _ = _two_saves(tmp_path, devices)
    ck.quarantine(2)
    assert ckpt_lib.all_checkpoints(str(tmp_path)) == [1]
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 1
    assert os.path.isdir(os.path.join(str(tmp_path),
                                      "step_00000002.poisoned"))


def test_wait_reraises_background_write_failure(tmp_path, devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    ck = ckpt_lib.Checkpointer(str(tmp_path))

    def always_fail(what):
        if what == "ckpt_write":
            raise OSError("injected: disk on fire")

    resilience.set_fault_hook(always_fail)
    try:
        ck.save(_state(mesh), 1, block=False)
        with pytest.raises(ckpt_lib.CheckpointWriteError,
                           match="disk on fire"):
            ck.wait()
    finally:
        resilience.set_fault_hook(None)
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) is None
    # The error is cleared once raised; the next save succeeds cleanly.
    ck.save(_state(mesh), 2, block=False)
    ck.wait()
    assert ckpt_lib.latest_checkpoint(str(tmp_path)) == 2


# ---------------------------------------------------------------------------
# watchdog fixes (satellite): polling block_until_ready, no late fires
# ---------------------------------------------------------------------------


def test_block_until_ready_timeout_no_thread_leak(devices):
    class NeverReady:
        def is_ready(self):
            return False

    before = threading.active_count()
    with pytest.raises(TimeoutError, match="not ready after"):
        watchdog.block_until_ready_with_timeout(
            {"a": NeverReady()}, timeout_s=0.05, poll_s=0.005)
    assert threading.active_count() == before  # old impl leaked one/call
    # Ready trees (device arrays AND plain host leaves) pass through.
    watchdog.block_until_ready_with_timeout(
        {"x": jnp.ones(3), "y": np.ones(3), "z": 1.0}, timeout_s=5.0)


def test_watchdog_stop_joins_thread():
    w = watchdog.Watchdog(timeout_s=0.02, fatal=False).start()
    time.sleep(0.05)
    w.stop()
    assert not w._thread.is_alive()


# ---------------------------------------------------------------------------
# launch.py supervisor (no jax in the child: pure restart-policy logic)
# ---------------------------------------------------------------------------


def _write_preempt_script(tmp_path):
    script = tmp_path / "fake_job.py"
    script.write_text(
        "import os, sys\n"
        "marker = sys.argv[1]\n"
        "if not os.path.exists(marker):\n"
        "    open(marker, 'w').write('x')\n"
        "    sys.exit(75)\n"
        "open(marker + '.resumed', 'w').write(' '.join(sys.argv[2:]))\n"
        "sys.exit(0)\n")
    return script


def test_supervisor_restarts_on_preempt_with_resume(tmp_path):
    script = _write_preempt_script(tmp_path)
    marker = tmp_path / "preempted"
    res = subprocess.run(
        [sys.executable, "launch.py", "--nprocs", "1",
         "--restart-policy", "on-preempt", "--max-restarts", "2",
         "--restart-backoff", "0.05", "--log-dir", str(tmp_path), "--",
         str(script), str(marker)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    assert "restart 1/2" in res.stderr, res.stderr
    assert (tmp_path / "preempted.resumed").read_text() == "--resume auto"


def test_supervisor_never_policy_propagates_exit(tmp_path):
    script = _write_preempt_script(tmp_path)
    res = subprocess.run(
        [sys.executable, "launch.py", "--nprocs", "1",
         "--log-dir", str(tmp_path), "--",
         str(script), str(tmp_path / "m")],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert res.returncode == resilience.PREEMPTED_EXIT_CODE
    assert not (tmp_path / "m.resumed").exists()


def test_supervisor_budget_exhausted_returns_last_code(tmp_path):
    script = tmp_path / "always75.py"
    script.write_text("import sys; sys.exit(75)\n")
    res = subprocess.run(
        [sys.executable, "launch.py", "--nprocs", "1",
         "--restart-policy", "on-preempt", "--max-restarts", "1",
         "--restart-backoff", "0.05", "--log-dir", str(tmp_path), "--",
         str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert res.returncode == resilience.PREEMPTED_EXIT_CODE
    assert "restart budget exhausted" in res.stderr, res.stderr


# ---------------------------------------------------------------------------
# end-to-end CLI drills (subprocess: real main.py, chaos injected)
# ---------------------------------------------------------------------------


def _train_cmd(ckdir, extra=()):
    return [sys.executable, "main.py", "--platform", "cpu",
            "--fake-devices", "2", "--config", "resnet18_cifar10",
            "--model", "resnet_micro", "--epochs", "1",
            "--steps-per-epoch", str(SPE), "--batch-size", "16",
            "--workers", "0", "--log-every", "1",
            "--checkpoint-dir", str(ckdir), *extra]


def _run(cmd, idx_log=None, timeout=420):
    env = dict(os.environ)
    if idx_log is not None:
        env[INDEX_LOG_ENV] = str(idx_log)
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


def _consumed(path):
    # First-yield wins and only in-epoch batches count: prefetch lookahead
    # legitimately overfetches past a kill point, and a resumed run re-logs
    # the batch it restarts on.
    out = {}
    for line in open(path):
        r = json.loads(line)
        key = (r["epoch"], r["batch"])
        if r["batch"] < SPE and key not in out:
            out[key] = r["indices"]
    return out


def _committed_steps(ckdir):
    return [d for d in sorted(os.listdir(ckdir)) if d.startswith("step_")
            and os.path.exists(os.path.join(ckdir, d, ckpt_lib.COMMIT_FILE))]


def test_cli_sigterm_takes_emergency_checkpoint(tmp_path):
    ckdir = tmp_path / "ck"
    res = _run(_train_cmd(ckdir, ["--chaos", "sigterm@step=3"]))
    assert res.returncode == resilience.PREEMPTED_EXIT_CODE, (
        res.returncode, res.stdout[-2000:], res.stderr[-2000:])
    assert "emergency checkpoint committed" in res.stderr + res.stdout
    assert _committed_steps(ckdir), "no committed emergency checkpoint"
    rows = [json.loads(line) for line in
            open(ckdir / chaos_lib.CHAOS_LOG)]
    assert len(rows) == 1 and rows[0]["event"] == "sigterm", rows
    assert rows[0]["step"] == 3, rows


def test_cli_nan_grad_rollback_is_sample_exact(tmp_path):
    flags = ["--telemetry", "--health-every", "1",
             "--checkpoint-every-steps", "2"]
    ref = _run(_train_cmd(tmp_path / "ck_ref", flags),
               idx_log=tmp_path / "ref_idx")
    assert ref.returncode == 0, ref.stdout[-2000:] + ref.stderr[-2000:]

    res = _run(_train_cmd(
        tmp_path / "ck", [*flags, "--anomaly-action", "rollback",
                          "--chaos", "nan_grad@step=3"]),
        idx_log=tmp_path / "idx")
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    err = res.stderr + res.stdout
    assert "anomaly rollback" in err, err[-3000:]
    assert _consumed(tmp_path / "idx") == _consumed(tmp_path / "ref_idx"), (
        "rollback run consumed a different sample stream")


def test_cli_ckpt_io_error_retries_then_commits(tmp_path):
    ckdir = tmp_path / "ck"
    res = _run(_train_cmd(ckdir, ["--checkpoint-every-steps", "2",
                                  "--chaos", "ckpt_io_error@save=1"]))
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    err = res.stderr + res.stdout
    assert "retriable io [ckpt_write] failed" in err, err[-3000:]
    assert _committed_steps(ckdir), "injected io errors lost the checkpoint"


def test_chaos_slow_host_parse_and_rank_gate():
    (ev,) = chaos_lib.parse_spec("slow_host@step=4:rank=1")
    assert (ev.name, ev.key, ev.value, ev.rank) == ("slow_host", "step", 4, 1)
    with pytest.raises(ValueError):
        chaos_lib.parse_spec("slow_host@batch=4")
    with pytest.raises(ValueError):
        chaos_lib.parse_spec("slow_host")


def test_chaos_slow_host_is_chronic_and_logs_once(tmp_path, monkeypatch):
    sleeps = []
    monkeypatch.setattr(chaos_lib.time, "sleep", sleeps.append)

    def drive(log_dir, rank):
        sleeps.clear()
        eng = chaos_lib.ChaosEngine("slow_host@step=2:rank=1", seed=7,
                                    log_dir=str(log_dir), rank=rank)
        eng.steps_per_epoch = SPE
        batch = {"x": np.ones(2, np.float32)}
        for g in range(6):
            out = eng.batch_hook(g // SPE, g % SPE, batch)
            assert out is batch  # never mutates the data
        return (log_dir / chaos_lib.CHAOS_LOG).read_text() \
            if (log_dir / chaos_lib.CHAOS_LOG).exists() else ""

    # Targeted rank: drags EVERY batch from the trip point on (chronic),
    # but chaos.jsonl records the injection exactly once.
    d1 = tmp_path / "a"
    d1.mkdir()
    log1 = drive(d1, rank=1)
    assert sleeps == [chaos_lib.ChaosEngine.SLOW_S] * 4  # batches 2..5
    rows = [json.loads(line) for line in log1.splitlines()]
    assert len(rows) == 1 and rows[0]["event"] == "slow_host"
    assert rows[0]["chronic"] is True

    # Same seed + spec -> byte-identical injection log.
    d2 = tmp_path / "b"
    d2.mkdir()
    assert drive(d2, rank=1) == log1

    # Other ranks: untouched, nothing logged.
    d3 = tmp_path / "c"
    d3.mkdir()
    assert drive(d3, rank=0) == ""
    assert sleeps == []
