import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib


def test_axes_and_default_mesh(devices):
    m = mesh_lib.build_mesh()
    assert m.axis_names == mesh_lib.AXES
    assert m.shape["data"] == 8
    assert all(m.shape[a] == 1 for a in mesh_lib.AXES if a != "data")


def test_wildcard_resolution(devices):
    m = mesh_lib.build_mesh({"data": -1, "fsdp": 2, "model": 2})
    assert m.shape["data"] == 2 and m.shape["fsdp"] == 2 and m.shape["model"] == 2


def test_bad_shapes(devices):
    with pytest.raises(ValueError):
        mesh_lib.build_mesh({"data": 3})  # 8 not divisible by 3
    with pytest.raises(ValueError):
        mesh_lib.MeshConfig(data=-1, fsdp=-1).resolve(8)


def test_seq_alias_builds_context_axis(devices):
    """``seq`` in a mesh-spec dict (the CLI's --mesh seq=N spelling and
    SNIPPETS.md [3]'s rules vocabulary) is the ``context`` axis."""
    m = mesh_lib.build_mesh({"data": -1, "seq": 4})
    assert m.shape["context"] == 4 and m.shape["data"] == 2


def test_axis_alias_conflict_rejected(devices):
    with pytest.raises(ValueError, match="twice"):
        mesh_lib.build_mesh({"seq": 2, "context": 2})


@pytest.mark.parametrize("world,expect_ctx", [(4, 4), (2, 2), (1, 1)])
def test_elastic_degrades_seq_axis_loudly(devices, world, expect_ctx, caplog):
    """A seq=4 mesh resumed at worlds 4/2/1: the context axis degrades to
    the largest divisor that fits and the degradation is logged loudly
    (the fixed-axis elastic contract extended to the seq axis)."""
    import logging

    with caplog.at_level(logging.WARNING, logger="pdtx"):
        m = mesh_lib.build_mesh({"data": -1, "seq": 4},
                                devices=devices[:world], elastic=True)
    assert m.shape["context"] == expect_ctx
    assert m.size == world
    if expect_ctx != 4:
        assert any("degraded" in r.message for r in caplog.records)
    else:
        assert not caplog.records


def test_elastic_seq_with_model_axis_shrinks_innermost_first(devices):
    """seq=2 x model=2 at a 2-device world: model (innermost) degrades
    before context."""
    m = mesh_lib.build_mesh({"data": -1, "seq": 2, "model": 2},
                            devices=devices[:2], elastic=True)
    assert m.shape["model"] == 1 and m.shape["context"] == 2


def test_batch_sharding_covers_devices(devices):
    m = mesh_lib.build_mesh({"data": 4, "fsdp": 2})
    assert mesh_lib.dp_size(m) == 8
    sh = mesh_lib.batch_sharding(m, ndim=2)
    x = jax.device_put(np.arange(16 * 3).reshape(16, 3).astype(np.float32), sh)
    assert len(x.addressable_shards) == 8
    assert all(s.data.shape == (2, 3) for s in x.addressable_shards)


def test_constrain_prunes_missing_axes(devices):
    m = mesh_lib.build_mesh({"data": 8})  # model axis size 1
    with mesh_lib.use_mesh(m):
        x = jax.numpy.zeros((8, 4))
        y = mesh_lib.constrain(x, P(("data", "fsdp"), "model"))
        assert y.shape == x.shape
    assert mesh_lib.current_mesh() is None


def test_single_device_mesh(devices):
    m = mesh_lib.single_device_mesh()
    assert mesh_lib.dp_size(m) == 1


def test_dcn_split_prefers_data_axis():
    # 2 slices over data=4: slice dim on data; everything else ICI-local.
    ici, dcn = mesh_lib.dcn_split((4, 2, 1, 1, 2, 2), 2)
    assert dcn == (2, 1, 1, 1, 1, 1)
    assert ici == (2, 2, 1, 1, 2, 2)


def test_dcn_split_falls_back_to_fsdp():
    # data=1 (pure-FSDP config): the slice dim lands on fsdp.
    ici, dcn = mesh_lib.dcn_split((1, 8, 1, 1, 1, 2), 4)
    assert dcn == (1, 4, 1, 1, 1, 1)
    assert ici == (1, 2, 1, 1, 1, 2)


def test_dcn_split_rejects_model_axis_crossing_dcn():
    # TP over DCN is never what you want; indivisible data/fsdp must raise.
    import pytest

    with pytest.raises(ValueError, match="data or fsdp"):
        mesh_lib.dcn_split((3, 1, 1, 1, 1, 8), 2)


class _FakeSliceDevice:
    """CPU device wrapper advertising a multislice ``slice_index``.

    Lets the hybrid ICI x DCN branch of build_mesh (VERDICT r3 missing #4:
    mesh.py's create_hybrid_device_mesh path had never executed anywhere)
    run on fake CPU devices: attribute access delegates to the wrapped
    device, so mesh_utils can read process_index/coords/etc.
    """

    def __init__(self, dev, slice_index):
        object.__setattr__(self, "_dev", dev)
        object.__setattr__(self, "slice_index", slice_index)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_dev"), name)

    def __repr__(self):
        return f"FakeSlice({self.slice_index}, {self._dev!r})"


def _fake_slices(devices, num_slices):
    per = len(devices) // num_slices
    return [_FakeSliceDevice(d, i // per) for i, d in enumerate(devices)]


def test_hybrid_mesh_slices_land_on_data_axis(devices):
    """2 fake slices x 4 devices: the hybrid branch must put the slice
    (DCN) dim on the outermost data axis and keep fsdp/model intra-slice."""
    m = mesh_lib.build_mesh({"data": 2, "fsdp": 2, "model": 2},
                            devices=_fake_slices(devices, 2))
    assert dict(m.shape) == {"data": 2, "fsdp": 2, "stage": 1, "expert": 1,
                             "context": 1, "model": 2}
    arr = m.devices
    for di in range(2):
        sub = arr[di]  # all devices at data index di
        slice_ids = {d.slice_index for d in sub.flat}
        assert slice_ids == {di}, (di, slice_ids)


def test_hybrid_mesh_slices_fall_back_to_fsdp_axis(devices):
    """Pure-FSDP config (data=1): the slice dim lands on fsdp, matching
    dcn_split's documented fallback."""
    m = mesh_lib.build_mesh({"data": 1, "fsdp": 4, "model": 2},
                            devices=_fake_slices(devices, 2))
    arr = m.devices
    for fi in range(4):
        sub = arr[0, fi]
        slice_ids = {d.slice_index for d in sub.flat}
        # fsdp axis split 2 slices x 2-per-slice: outer half slice 0
        assert slice_ids == {fi // 2}, (fi, slice_ids)


def test_hybrid_mesh_rejects_indivisible_dp(devices):
    """Neither data nor fsdp divisible by the slice count must raise (TP
    over DCN is never constructed silently)."""
    with pytest.raises(ValueError, match="data or fsdp"):
        mesh_lib.build_mesh({"data": 1, "fsdp": 1, "model": 8},
                            devices=_fake_slices(devices, 2))
