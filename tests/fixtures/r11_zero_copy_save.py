"""Regression fixture: the r11 async-checkpoint corruption, pre-fix shape.

This module reproduces ``Checkpointer.save`` as it looked BEFORE the r11
fix: per-shard snapshots taken with ``np.asarray`` (a zero-copy view of the
device buffer) and handed to the background write thread. The caller then
donates the state to the next train step, XLA recycles the buffer memory
for activations, and the thread serializes garbage — with a valid CRC,
since the checksum is computed over whatever bytes hit disk.

Never imported by the package. tests/test_graftlint.py lints this file and
asserts GL001 flags the ``np.asarray`` snapshot; the fixed code
(``np.array`` copies) must come back clean.
"""
import os
import threading

import numpy as np


class BrokenCheckpointer:
    """Pre-r11 save(): zero-copy shard snapshots escape into the writer."""

    def save(self, state, step, directory):
        shards = {}
        for path, arr in state.items():
            regions = []
            for sh in arr.addressable_shards:
                # BUG (r11): np.asarray aliases the device buffer; once the
                # caller donates the state this memory is recycled under
                # the background thread mid-write.
                regions.append((list(sh.index), np.asarray(sh.data)))
            shards[path] = regions

        def write():
            for path, regions in shards.items():
                for i, (idx, data) in enumerate(regions):
                    np.save(os.path.join(directory, f"{path}.{i}.npy"), data)

        t = threading.Thread(target=write, daemon=True)
        t.start()
        return t
