"""Persistent executable cache (core/xcache.py): fingerprint discipline,
save/load round trip, and corruption quarantine."""

import json
import logging
import os
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import xcache


@pytest.fixture(autouse=True)
def _pdtx_reaches_caplog():
    """Trainer tests earlier in the suite run setup_logging(), which sets
    propagate=False on 'pdtx' — caplog's root handler would miss every
    MISS/HIT record here. Restore propagation for this module."""
    log = logging.getLogger("pdtx")
    prev = log.propagate
    log.propagate = True
    yield
    log.propagate = prev


def _mesh():
    return jax.sharding.Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("data", "model"))


def _cfg(**over):
    base = {"model": "llama_tiny", "seq_len": 32, "global_batch_size": 8,
            "grad_accum_steps": 1, "precision": "fp32", "strategy": "dp",
            "optimizer": "adamw", "remat": False}
    base.update(over)
    return types.SimpleNamespace(**base)


def test_skeleton_roundtrip_and_rejects_fancy_containers():
    tree = {"loss": 1.0, "aux": ({"acc": 2.0}, [3.0, {"lr": 4.0}])}
    skel = xcache._skeleton(tree)
    json.dumps(skel)  # must be JSON-able: it is stored in meta.json
    rebuilt = xcache._unskeleton(skel)
    # Same treedef, leaves reset to placeholder floats.
    assert (jax.tree_util.tree_structure(rebuilt)
            == jax.tree_util.tree_structure(tree))
    assert jax.tree_util.tree_leaves(rebuilt) == [0.0] * 4
    with pytest.raises(TypeError):
        xcache._skeleton({1: "non-string key"})


def test_fingerprint_key_stable_and_knob_sensitive():
    mesh = _mesh()
    x = jnp.ones((4, 2), jnp.float32)
    key = xcache.cache_key(
        xcache.fingerprint(mesh=mesh, config=_cfg(), example_args=(x,)))
    again = xcache.cache_key(
        xcache.fingerprint(mesh=mesh, config=_cfg(), example_args=(x,)))
    assert key == again  # deterministic across calls

    # Every traced knob, shape change, or extra tag must move the key — a
    # stale hit is silent wrong math.
    for fields in (
            xcache.fingerprint(mesh=mesh, config=_cfg(grad_accum_steps=2),
                               example_args=(x,)),
            xcache.fingerprint(mesh=mesh, config=_cfg(precision="bf16"),
                               example_args=(x,)),
            xcache.fingerprint(mesh=mesh, config=_cfg(),
                               example_args=(jnp.ones((8, 2), jnp.float32),)),
            xcache.fingerprint(mesh=mesh, config=_cfg(), example_args=(x,),
                               extra={"phase": "serve"}),
    ):
        assert xcache.cache_key(fields) != key

    # Untraced attributes must NOT invalidate (no spurious cold compiles).
    cfg = _cfg()
    cfg.checkpoint_every_steps = 1234
    assert xcache.cache_key(xcache.fingerprint(
        mesh=mesh, config=cfg, example_args=(x,))) == key


def test_save_load_roundtrip_executes_warm(tmp_path, caplog):
    x = jnp.arange(4, dtype=jnp.float32)
    compiled = jax.jit(lambda v: v * 2.0 + 1.0).lower(x).compile()
    fields = xcache.fingerprint(mesh=_mesh(), example_args=(x,))

    with caplog.at_level("WARNING", logger="pdtx"):
        assert xcache.load(str(tmp_path), fields) is None  # empty cache
    assert any("MISS" in r.message for r in caplog.records)

    if not xcache.save(str(tmp_path), fields, compiled):
        pytest.skip("executable serialization unsupported on this backend")
    caplog.clear()
    with caplog.at_level("WARNING", logger="pdtx"):
        warm = xcache.load(str(tmp_path), fields)
    assert warm is not None
    assert any("HIT" in r.message for r in caplog.records)
    np.testing.assert_allclose(np.asarray(warm(x)),
                               np.asarray(x) * 2.0 + 1.0)


def test_load_quarantines_crc_corruption_and_recovers(tmp_path, caplog):
    x = jnp.arange(3, dtype=jnp.float32)
    compiled = jax.jit(lambda v: v - 1.0).lower(x).compile()
    fields = xcache.fingerprint(mesh=_mesh(), example_args=(x,))
    if not xcache.save(str(tmp_path), fields, compiled):
        pytest.skip("executable serialization unsupported on this backend")
    entry = os.path.join(xcache.cache_dir(str(tmp_path)),
                         xcache.cache_key(fields))
    with open(os.path.join(entry, xcache.EXECUTABLE_FILE), "r+b") as fh:
        fh.write(b"\xde\xad\xbe\xef")  # flip leading bytes

    with caplog.at_level("WARNING", logger="pdtx"):
        assert xcache.load(str(tmp_path), fields) is None
    assert any("CRC mismatch" in r.message for r in caplog.records)
    assert not os.path.isdir(entry)  # quarantined aside, never half-trusted
    assert os.path.isdir(entry + ".corrupt")

    # The recompile path re-saves under the same key and hits again.
    assert xcache.save(str(tmp_path), fields, compiled)
    assert xcache.load(str(tmp_path), fields) is not None


def test_load_refuses_fingerprint_mismatch_under_same_key(tmp_path, caplog):
    x = jnp.arange(3, dtype=jnp.float32)
    compiled = jax.jit(lambda v: v + 2.0).lower(x).compile()
    fields = xcache.fingerprint(mesh=_mesh(), example_args=(x,))
    if not xcache.save(str(tmp_path), fields, compiled):
        pytest.skip("executable serialization unsupported on this backend")
    entry = os.path.join(xcache.cache_dir(str(tmp_path)),
                         xcache.cache_key(fields))
    meta_path = os.path.join(entry, xcache.META_FILE)
    meta = json.load(open(meta_path))
    meta["fields"]["jax_version"] = "0.0.0-stale"
    with open(meta_path, "w") as fh:
        json.dump(meta, fh)

    with caplog.at_level("WARNING", logger="pdtx"):
        assert xcache.load(str(tmp_path), fields) is None
    assert any("fingerprint mismatch" in r.message
               and "stale" in r.message for r in caplog.records)
    assert os.path.isdir(entry)  # a mismatch is not corruption

    # Torn meta IS corruption: quarantine.
    with open(meta_path, "w") as fh:
        fh.write('{"crc32": 12')
    caplog.clear()
    with caplog.at_level("WARNING", logger="pdtx"):
        assert xcache.load(str(tmp_path), fields) is None
    assert os.path.isdir(entry + ".corrupt")


def test_reconstruct_mode_rebuilds_treedefs_from_live_example(
        tmp_path, monkeypatch):
    state = {"w": jnp.ones((2, 2), jnp.float32)}
    batch = {"x": jnp.full((2,), 3.0, jnp.float32)}

    def step(state, batch):
        new = {"w": state["w"] + 1.0}
        return new, {"loss": jnp.sum(batch["x"]), "aux": (jnp.float32(0.5),)}

    compiled = jax.jit(step).lower(state, batch).compile()
    metrics = jax.tree_util.tree_map(
        lambda a: a, step(state, batch)[1])  # same treedef as the output
    fields = xcache.fingerprint(mesh=_mesh(), example_args=(state, batch))

    # Force the trainer's real-world condition: treedefs that refuse to
    # pickle (the TrainState's optax closures), so save() must fall back
    # to reconstruct mode.
    def _no_pickle(_):
        raise TypeError("cannot pickle closure")

    monkeypatch.setattr(xcache.pickle, "dumps", _no_pickle)
    if not xcache.save(str(tmp_path), fields, compiled,
                       example=(state, batch), metrics=metrics):
        pytest.skip("executable serialization unsupported on this backend")
    entry = os.path.join(xcache.cache_dir(str(tmp_path)),
                         xcache.cache_key(fields))
    meta = json.load(open(os.path.join(entry, xcache.META_FILE)))
    assert meta["tree_mode"] == "reconstruct"
    monkeypatch.undo()

    # Without the live example the entry is unusable — loudly cold.
    assert xcache.load(str(tmp_path), fields) is None

    warm = xcache.load(str(tmp_path), fields, example=(state, batch))
    assert warm is not None
    new_state, out = warm(state, batch)
    np.testing.assert_allclose(np.asarray(new_state["w"]), 2.0)
    np.testing.assert_allclose(float(out["loss"]), 6.0)
    assert isinstance(out["aux"], tuple)  # treedef faithfully rebuilt


def test_compile_cached_modes(tmp_path):
    x = jnp.arange(5, dtype=jnp.float32)
    fields = xcache.fingerprint(mesh=_mesh(), example_args=(x,))
    lowered = jax.jit(lambda v: v * 3.0).lower(x)

    compiled, mode = xcache.compile_cached(lowered, None, fields)
    assert mode == "cold"  # no cache root: plain compile

    compiled, mode = xcache.compile_cached(lowered, str(tmp_path), fields)
    assert mode == "cold"
    if not os.path.isdir(os.path.join(xcache.cache_dir(str(tmp_path)),
                                      xcache.cache_key(fields))):
        pytest.skip("executable serialization unsupported on this backend")
    compiled, mode = xcache.compile_cached(lowered, str(tmp_path), fields)
    assert mode == "warm"
    np.testing.assert_allclose(np.asarray(compiled(x)), np.asarray(x) * 3.0)
