"""Parallel-equivalence tests (SURVEY.md §4.2): the single-device step is the
numerical oracle — an N-device data-parallel / FSDP step on a sharded batch
must match it on the concatenated batch within tolerance."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.core import optim, train_loop
from pytorch_distributed_training_example_tpu.data import prefetch
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
from pytorch_distributed_training_example_tpu.utils.config import Config


def _build(mesh, strategy, seed=0, lr=0.1, model="resnet_micro"):
    cfg = Config(lr=lr, warmup_epochs=0.0, grad_clip=0.0, weight_decay=1e-4)
    bundle = registry.create_model(model, num_classes=10, image_size=32,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
    rules = sharding_lib.strategy_rules(strategy, bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx, bundle.input_template,
                                          mesh, rules, seed=seed)
    task = train_loop.get_task(bundle.task)
    step = jax.jit(train_loop.make_train_step(task),
                   donate_argnums=0)
    return state, step


def _batch(n=16, seed=0):
    r = np.random.RandomState(seed)
    return {"image": r.randn(n, 32, 32, 3).astype(np.float32),
            "label": (np.arange(n) % 10).astype(np.int32)}


def _run_steps(mesh, strategy, n_steps=3, model="resnet_micro"):
    state, step = _build(mesh, strategy, model=model)
    with mesh_lib.use_mesh(mesh):
        sh = mesh_lib.batch_sharding(mesh)
        metrics = None
        for i in range(n_steps):
            b = prefetch.shard_batch(_batch(seed=i), sh)
            state, metrics = step(state, b)
        params = jax.device_get(state.params)
    return params, {k: float(v) for k, v in metrics.items()}


@pytest.mark.parametrize("mesh_cfg,strategy", [
    ({"data": 8}, "dp"),
    ({"data": 2, "fsdp": 4}, "fsdp"),
    ({"data": 1, "fsdp": 8}, "fsdp"),
])
def test_parallel_matches_single_device(devices, mesh_cfg, strategy):
    ref_mesh = mesh_lib.single_device_mesh()
    ref_params, ref_metrics = _run_steps(ref_mesh, "dp")
    par_mesh = mesh_lib.build_mesh(mesh_cfg)
    par_params, par_metrics = _run_steps(par_mesh, strategy)

    assert np.isclose(ref_metrics["loss"], par_metrics["loss"], rtol=1e-4)
    flat_ref = jax.tree.leaves(ref_params)
    flat_par = jax.tree.leaves(par_params)
    for a, b in zip(flat_ref, flat_par):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


@pytest.mark.slow
def test_parallel_matches_single_device_resnet18(devices):
    """Full-fidelity oracle check on the real reference model (the fast
    variants above use resnet_micro)."""
    ref_params, ref_metrics = _run_steps(
        mesh_lib.single_device_mesh(), "dp", model="resnet18")
    par_params, par_metrics = _run_steps(
        mesh_lib.build_mesh({"data": 2, "fsdp": 4}), "fsdp", model="resnet18")
    assert np.isclose(ref_metrics["loss"], par_metrics["loss"], rtol=1e-4)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(par_params)):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5)


def test_fsdp_actually_shards_params(devices):
    mesh = mesh_lib.build_mesh({"data": 1, "fsdp": 8})
    state, _ = _build(mesh, "fsdp")
    sharded = [
        p for p in jax.tree.leaves(state.params)
        if not p.sharding.is_fully_replicated
    ]
    assert sharded, "FSDP produced no sharded parameters"
    # Optimizer (momentum) state must shard identically to its params.
    sharded_opt = [
        p for p in jax.tree.leaves(state.opt_state)
        if hasattr(p, "sharding") and not p.sharding.is_fully_replicated
    ]
    assert len(sharded_opt) >= len(sharded)


def test_dp_replicates_params(devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    state, _ = _build(mesh, "dp")
    assert all(p.sharding.is_fully_replicated for p in jax.tree.leaves(state.params))


def test_grad_accum_matches_full_batch(devices):
    """grad_accum=G over batch B == one step on the full B (same update):
    the in-step scan averages microbatch grads before the optimizer."""
    mesh = mesh_lib.build_mesh({"data": 8})
    cfg = Config(lr=0.1, warmup_epochs=0.0, grad_clip=0.0, weight_decay=1e-4)
    bundle = registry.create_model("resnet_micro", num_classes=10,
                                   image_size=32, dtype=jnp.float32,
                                   param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    task = train_loop.get_task(bundle.task)
    b = _batch(n=32, seed=7)

    results = {}
    for accum in (1, 4):
        state = train_loop.create_train_state(
            bundle.module, tx, bundle.input_template, mesh, rules, seed=0)
        step = jax.jit(train_loop.make_train_step(task, accum),
                       donate_argnums=0)
        with mesh_lib.use_mesh(mesh):
            sh = mesh_lib.batch_sharding(mesh)
            state, m = step(state, prefetch.shard_batch(b, sh))
            results[accum] = (jax.device_get(state.params), float(m["loss"]))

    # Microbatch BN statistics differ from full-batch BN by design (norm
    # over 8 vs 32 examples), so compare the mean loss loosely but the
    # parameter UPDATE tightly modulo that effect.
    assert np.isclose(results[1][1], results[4][1], rtol=0.05)
    for a, c in zip(jax.tree.leaves(results[1][0]),
                    jax.tree.leaves(results[4][0])):
        np.testing.assert_allclose(a, c, rtol=0.05, atol=5e-3)


def test_grad_accum_matches_full_batch_lm(devices):
    """No BatchNorm in the LM family -> accumulation must match the full
    batch tightly."""
    mesh = mesh_lib.build_mesh({"data": 8})
    cfg = Config(lr=1e-2, warmup_epochs=0.0, optimizer="sgd", grad_clip=0.0,
                 weight_decay=0.0)
    bundle = registry.create_model("llama_tiny", seq_len=32,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    task = train_loop.get_task(bundle.task)
    r = np.random.RandomState(0)
    toks = r.randint(0, 512, (16, 33)).astype(np.int32)
    b = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}

    results = {}
    for accum in (1, 4):
        state = train_loop.create_train_state(
            bundle.module, tx, bundle.input_template, mesh, rules, seed=0)
        step = jax.jit(train_loop.make_train_step(task, accum),
                       donate_argnums=0)
        with mesh_lib.use_mesh(mesh):
            sh = mesh_lib.batch_sharding(mesh)
            state, m = step(state, prefetch.shard_batch(b, sh))
            results[accum] = (jax.device_get(state.params), float(m["loss"]))

    assert np.isclose(results[1][1], results[4][1], rtol=1e-5)
    for a, c in zip(jax.tree.leaves(results[1][0]),
                    jax.tree.leaves(results[4][0])):
        np.testing.assert_allclose(a, c, rtol=1e-4, atol=1e-6)


def test_train_decreases_loss(devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    state, step = _build(mesh, "dp", lr=0.4)
    # Separable signal (fixed per-class pattern + noise): the micro oracle
    # net lacks the capacity to memorize pure noise quickly.
    r = np.random.RandomState(42)
    labels = (np.arange(64) % 10).astype(np.int32)
    patterns = r.randn(10, 32, 32, 3).astype(np.float32)
    b0 = {"image": 0.3 * r.randn(64, 32, 32, 3).astype(np.float32)
          + patterns[labels],
          "label": labels}
    with mesh_lib.use_mesh(mesh):
        sh = mesh_lib.batch_sharding(mesh)
        first = None
        for _ in range(25):  # same separable batch -> loss must collapse
            b = prefetch.shard_batch(b0, sh)
            state, m = step(state, b)
            if first is None:
                first = float(m["loss"])
        last = float(m["loss"])
    assert last < first * 0.5, (first, last)
