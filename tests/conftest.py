"""Test harness: 8 fake CPU devices in one process (SURVEY.md §4.2).

Env must be set before jax initializes its backends; pytest imports conftest
before any test module, so doing it at module import time is safe. The axon
sitecustomize exports JAX_PLATFORMS=axon — override it to keep CI off the
real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# jax 0.4.x API-compat patches (CompilerParams name, interpret-mode context)
# must land before any test module imports pallas symbols.
from pytorch_distributed_training_example_tpu.ops import pallas_compat  # noqa: E402,F401

jax.config.update("jax_platforms", "cpu")
# jax 0.4.x defaults threefry_partitionable=False, where sharded param init
# produces DIFFERENT bits than single-device init — the TP-vs-single-device
# equivalence tests then compare two different models. True is the jax 0.5+
# default and what main.py sets for real runs; mirror it here.
jax.config.update("jax_threefry_partitionable", True)
# Persistent compile cache: XLA:CPU compiles dominate suite wall time
# (25s -> ~7s for a ResNet-18 train step on re-runs). Machine-local cache in
# /tmp — never shipped; safe because re-runs happen on the same host.
jax.config.update("jax_compilation_cache_dir",
                  "/tmp/pytorch_distributed_training_example_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
