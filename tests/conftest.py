"""Test harness: 8 fake CPU devices in one process (SURVEY.md §4.2).

Env must be set before jax initializes its backends; pytest imports conftest
before any test module, so doing it at module import time is safe. The axon
sitecustomize exports JAX_PLATFORMS=axon — override it to keep CI off the
real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
