"""C++ batch engine vs the Python loader (skipped when no toolchain)."""

import os

import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.data import native_loader as nl
from pytorch_distributed_training_example_tpu.data.sampler import ShardedSampler

pytestmark = pytest.mark.skipif(not nl.available(),
                                reason="native engine unavailable (no g++)")


def test_gather_matches_numpy():
    data = np.random.RandomState(0).randint(0, 1000, (50, 16)).astype(np.int32)
    eng = nl.NativeBatchEngine.gather(data)
    idx = np.array([5, 0, 49, 17, 17])
    out = np.empty((5, 16), np.int32)
    eng.submit(0, idx, out)
    eng.wait(0)
    np.testing.assert_array_equal(out, data[idx])
    eng.close()


def test_image_normalize_matches_numpy():
    imgs = np.random.RandomState(1).randint(0, 256, (12, 8, 8, 3), np.uint8)
    mean, std = [0.4, 0.5, 0.6], [0.2, 0.3, 0.25]
    eng = nl.NativeBatchEngine.image(imgs, mean, std, augment=False)
    out = np.empty((12, 8, 8, 3), np.float32)
    eng.submit(0, np.arange(12), out)
    eng.wait(0)
    ref = (imgs.astype(np.float32) / 255.0 - np.asarray(mean, np.float32)) \
        / np.asarray(std, np.float32)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    eng.close()


def test_augment_deterministic_per_seed():
    imgs = np.random.RandomState(2).randint(0, 256, (6, 8, 8, 3), np.uint8)
    eng = nl.NativeBatchEngine.image(imgs, [0.5] * 3, [0.25] * 3, augment=True)
    a = np.empty((6, 8, 8, 3), np.float32)
    b = np.empty_like(a)
    c = np.empty_like(a)
    eng.submit(0, np.arange(6), a, seed=7)
    eng.submit(1, np.arange(6), b, seed=7)
    eng.submit(2, np.arange(6), c, seed=8)
    for i in range(3):
        eng.wait(i)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    eng.close()


def test_augment_invariant_to_chunking():
    """Per-sample RNG is keyed on the DATASET index, so splitting a batch
    across jobs (different --workers / chunk sizes) must not change the
    augmentation (ADVICE r2: chunk-relative seeding was not reproducible)."""
    imgs = np.random.RandomState(5).randint(0, 256, (6, 8, 8, 3), np.uint8)
    eng = nl.NativeBatchEngine.image(imgs, [0.5] * 3, [0.25] * 3, augment=True)
    whole = np.empty((6, 8, 8, 3), np.float32)
    split = np.empty_like(whole)
    eng.submit(0, np.arange(6), whole, seed=7)
    eng.submit(1, np.arange(3), split[:3], seed=7)        # chunk 1
    eng.submit(2, np.arange(3, 6), split[3:], seed=7)     # chunk 2
    for i in range(3):
        eng.wait(i)
    np.testing.assert_array_equal(whole, split)
    # reordered indices still get their own per-index stream
    perm = np.array([3, 1, 5, 0, 4, 2])
    reord = np.empty_like(whole)
    eng.submit(3, perm, reord, seed=7)
    eng.wait(3)
    np.testing.assert_array_equal(reord, whole[perm])
    eng.close()


def test_native_dataloader_iterates():
    imgs = np.random.RandomState(3).randint(0, 256, (40, 8, 8, 3), np.uint8)
    labels = np.arange(40) % 10
    sampler = ShardedSampler(40, 2, 0, shuffle=True, seed=0, drop_last=True)
    dl = nl.NativeDataLoader(imgs, labels, sampler, batch_size=4,
                             mean=[0.5] * 3, std=[0.25] * 3, augment=False)
    batches = list(dl)
    assert len(batches) == len(dl) == 5
    assert batches[0]["image"].shape == (4, 8, 8, 3)
    assert batches[0]["image"].dtype == np.float32
    # second epoch reshuffles
    dl.set_epoch(1)
    batches2 = list(dl)
    assert not np.array_equal(batches[0]["label"], batches2[0]["label"])
    # and the contents match the python gather for the same sampler order
    sampler2 = ShardedSampler(40, 2, 0, shuffle=True, seed=0, drop_last=True)
    sampler2.set_epoch(1)
    idx = sampler2.local_indices()[:4]
    ref = (imgs[idx].astype(np.float32) / 255.0 - 0.5) / 0.25
    np.testing.assert_allclose(batches2[0]["image"], ref, atol=1e-5)


def test_native_dataloader_start_batch_matches_suffix():
    """start_batch (mid-epoch resume) on the native loader yields exactly
    the suffix of the full epoch stream — same contract as DataLoader."""
    imgs = np.random.RandomState(5).randint(0, 256, (48, 8, 8, 3), np.uint8)
    labels = np.arange(48) % 10
    mk = lambda: nl.NativeDataLoader(
        imgs, labels, ShardedSampler(48, 1, 0, shuffle=True, seed=2,
                                     drop_last=True),
        batch_size=4, mean=[0.5] * 3, std=[0.25] * 3, augment=False)
    full = list(mk())
    dl = mk()
    dl.start_batch = 7
    tail = list(dl)
    assert len(tail) == len(full) - 7
    for a, b in zip(full[7:], tail):
        np.testing.assert_array_equal(a["label"], b["label"])
        np.testing.assert_allclose(a["image"], b["image"])


def test_native_dataloader_early_abandon_drains():
    """Breaking out of iteration must not leave C++ jobs writing into freed bufs."""
    imgs = np.random.RandomState(4).randint(0, 256, (64, 8, 8, 3), np.uint8)
    labels = np.arange(64) % 10
    sampler = ShardedSampler(64, 1, 0, shuffle=False, drop_last=True)
    dl = nl.NativeDataLoader(imgs, labels, sampler, batch_size=4,
                             mean=[0.5] * 3, std=[0.25] * 3, augment=False,
                             prefetch=4)
    for ep in range(3):  # repeated early abandonment across epochs
        dl.set_epoch(ep)
        it = iter(dl)
        next(it)
        next(it)
        it.close()
    # full pass afterwards still correct
    first = next(iter(dl))
    idx = dl.sampler.local_indices()[:4]
    ref = (imgs[idx].astype(np.float32) / 255.0 - 0.5) / 0.25
    np.testing.assert_allclose(first["image"], ref, atol=1e-5)


def test_token_loader_matches_python_bitforbit(tmp_path):
    """Native window-gather over a token file == TokenFileDataset through the
    Python loader, same sampler order."""
    from pytorch_distributed_training_example_tpu.data.datasets import (
        TokenFileDataset)
    from pytorch_distributed_training_example_tpu.data.loader import (
        DataLoader, build_image_loader)

    rng = np.random.RandomState(5)
    toks = rng.randint(0, 50000, 4097).astype(np.uint16)
    path = tmp_path / "tokens.bin"
    toks.tofile(path)
    ds = TokenFileDataset(str(path), seq_len=128)
    assert len(ds) == 32

    sampler = ShardedSampler(len(ds), shuffle=True, seed=2, drop_last=True)
    native = build_image_loader(ds, sampler, batch_size=4, workers=2)
    assert isinstance(native, nl.NativeTokenDataLoader)
    sampler_py = ShardedSampler(len(ds), shuffle=True, seed=2, drop_last=True)
    python = DataLoader(ds, 4, sampler_py, num_workers=0)

    native.set_epoch(1)
    python.set_epoch(1)
    nb, pb = list(native), list(python)
    assert len(nb) == len(pb) == 8
    for a, b in zip(nb, pb):
        np.testing.assert_array_equal(a["tokens"], b["tokens"])
        np.testing.assert_array_equal(a["targets"], b["targets"])
        assert a["tokens"].dtype == np.int32


def test_native_dataloader_rejects_drop_last_false():
    imgs = np.zeros((8, 4, 4, 3), np.uint8)
    with pytest.raises(ValueError, match="drop_last"):
        nl.NativeDataLoader(imgs, np.zeros(8), ShardedSampler(8), 4,
                            [0.5] * 3, [0.25] * 3, False, drop_last=False)
