"""Dropless MoE: the ragged grouped-matmul kernel and its wiring.

Three layers of guarantee, mirroring how the sort-dispatch suite is built:

1. Kernel parity (interpret mode off-TPU, so the REAL Pallas kernel
   bodies run): ``gmm`` / ``grouped_ffn`` forward and custom_vjp grads
   against a dense segment-einsum reference, across uneven / empty /
   single-expert-takes-all segments, E in {2, 8}, fp32 and bf16.
2. Module oracle: ``dispatch_impl="dropless"`` equals the einsum path at
   a never-drop capacity factor — the routing decisions are bitwise the
   same (shared fp32 router), so outputs, aux/z losses and parameter
   grads must match to accumulation tolerance, and the drop-fraction
   telemetry must be the exact constant 0.0.
3. Wiring: a full train step on the GQA llama_moe_tiny trunk under an
   fsdp x ep mesh matches the einsum oracle loss/params, an EP-mesh leg
   guards the jax 0.4.x sharded-operand gather miscompile workaround,
   and the capacity-clamp warning fires (once) for the non-dropless
   paths it protects.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.ops import (
    grouped_matmul as gmm_lib)
from pytorch_distributed_training_example_tpu.parallel import moe as moe_lib
from pytorch_distributed_training_example_tpu.parallel import (
    sharding as sharding_lib)

D = 16

# Never-drop capacity factor for the einsum oracle: capacity >= k*T for
# every test shape here, so within_cap keeps every routed token.
NEVER_DROP_CF = 100.0


def _segments(rng, E, Tk, *, empty=None, takes_all=None):
    """Random ragged segment sizes; optionally force expert ``empty`` to
    zero rows or expert ``takes_all`` to own every row."""
    if takes_all is not None:
        counts = np.zeros(E, np.int64)
        counts[takes_all] = Tk
    else:
        counts = rng.multinomial(Tk, np.ones(E) / E)
        if empty is not None:
            nxt = (empty + 1) % E
            counts[nxt] += counts[empty]
            counts[empty] = 0
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    return starts.astype(np.int32), counts.astype(np.int32)


def _ref_gmm(x, w, starts, counts):
    seg = np.zeros(x.shape[0], np.int32)
    for e in range(w.shape[0]):
        seg[int(starts[e]):int(starts[e]) + int(counts[e])] = e
    return jnp.einsum("td,tdf->tf", x, w[seg],
                      preferred_element_type=jnp.float32).astype(x.dtype)


def _ref_ffn(x, w_up, w_down, starts, counts):
    h = jax.nn.gelu(_ref_gmm(x, w_up, starts, counts))
    return _ref_gmm(h, w_down, starts, counts)


_TOLS = {  # dtype -> (fwd rtol, fwd atol, grad rtol, grad atol)
    "float32": (1e-5, 1e-6, 1e-4, 1e-5),
    # bf16 grad atol: dw sums bf16 products over a whole segment in a
    # different association order than XLA's transpose, so the noise
    # floor is ~eps_bf16 * sum_t |x_t * g_t| — with ~32-row segments and
    # O(1) entries that is a few tenths absolute on near-cancelling
    # elements (fp32 runs of the same cases agree to 1e-4: the math,
    # not the kernel, is the noise source).
    "bfloat16": (3e-2, 3e-2, 6e-2, 3e-1),
}


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("E,Tk,segs", [
    (2, 24, {}),                 # uneven random segments
    (2, 24, {"empty": 0}),       # an empty expert (still gets a dw block)
    (8, 256, {}),                # many experts
    (8, 256, {"empty": 3}),      # empty expert mid-pack
    (4, 64, {"takes_all": 2}),   # one expert owns every token
])
def test_gmm_matches_dense_reference(E, Tk, segs, dtype):
    """Kernel forward + custom_vjp grads == dense einsum over the same
    segment map, in interpret mode (the actual kernel bodies execute)."""
    rng = np.random.default_rng(0)
    rt, at, grt, gat = _TOLS[np.dtype(dtype).name]
    starts, counts = _segments(rng, E, Tk, **segs)
    x = jnp.asarray(rng.standard_normal((Tk, D)), dtype)
    w = jnp.asarray(rng.standard_normal((E, D, 2 * D)) * 0.1, dtype)
    sj, cj = jnp.asarray(starts), jnp.asarray(counts)

    out = gmm_lib.gmm(x, w, sj, cj)
    ref = _ref_gmm(x, w, starts, counts)
    assert out.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=rt, atol=at)

    def loss_k(x, w):
        return jnp.sum(jnp.sin(gmm_lib.gmm(x, w, sj, cj)
                               .astype(jnp.float32)))

    def loss_r(x, w):
        return jnp.sum(jnp.sin(_ref_gmm(x, w, starts, counts)
                               .astype(jnp.float32)))

    gk = jax.grad(loss_k, argnums=(0, 1))(x, w)
    gr = jax.grad(loss_r, argnums=(0, 1))(x, w)
    for a, b in zip(gk, gr):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=grt, atol=gat)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_grouped_ffn_matches_dense_reference(dtype):
    """The padded-layout FFN composition (one relayout round trip across
    up-proj -> gelu -> down-proj) == the dense per-segment MLP."""
    rng = np.random.default_rng(1)
    rt, at, grt, gat = _TOLS[np.dtype(dtype).name]
    E, Tk = 8, 192
    starts, counts = _segments(rng, E, Tk, empty=5)
    x = jnp.asarray(rng.standard_normal((Tk, D)), dtype)
    w_up = jnp.asarray(rng.standard_normal((E, D, 32)) * 0.1, dtype)
    w_down = jnp.asarray(rng.standard_normal((E, 32, D)) * 0.1, dtype)
    sj, cj = jnp.asarray(starts), jnp.asarray(counts)

    out = gmm_lib.grouped_ffn(x, w_up, w_down, sj, cj)
    ref = _ref_ffn(x, w_up, w_down, starts, counts)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=rt, atol=at)

    def loss(fn):
        def f(x, wu, wd):
            return jnp.sum(jnp.sin(fn(x, wu, wd).astype(jnp.float32)))
        return jax.grad(f, argnums=(0, 1, 2))(x, w_up, w_down)

    gk = loss(lambda x, wu, wd: gmm_lib.grouped_ffn(x, wu, wd, sj, cj))
    gr = loss(lambda x, wu, wd: _ref_ffn(x, wu, wd, starts, counts))
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=grt, atol=gat)


def _blocks(E, k, **kw):
    mk = lambda impl, cf: moe_lib.MoEBlock(  # noqa: E731
        num_experts=E, ffn_dim=32, top_k=k, capacity_factor=cf,
        dispatch_impl=impl, **kw)
    return mk("dropless", 1.0), mk("einsum", NEVER_DROP_CF)


def _x(seed=7, b=2, t=32):
    return jnp.asarray(np.random.RandomState(seed).randn(b, t, D),
                       jnp.float32)


def _drop_leaves(tel):
    return [leaf for path, leaf
            in jax.tree_util.tree_leaves_with_path(tel)
            if "drop" in jax.tree_util.keystr(path)]


@pytest.mark.parametrize("E,k", [(4, 2), (4, 1), (8, 2)])
def test_dropless_matches_einsum_oracle(E, k):
    """dropless == einsum at a never-drop capacity factor: same forward,
    same aux/z losses, same param/input grads; drop fraction is the
    constant 0.0 (the sow short-circuits — no mask work to DCE)."""
    d_blk, e_blk = _blocks(E, k)
    x = _x()
    params = d_blk.init(jax.random.PRNGKey(0), x)["params"]

    out_d, var_d = d_blk.apply({"params": params}, x,
                               mutable=["telemetry", "losses"])
    out_e, var_e = e_blk.apply({"params": params}, x,
                               mutable=["telemetry", "losses"])
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_e),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(var_d["losses"]),
                    jax.tree.leaves(var_e["losses"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    drops = _drop_leaves(var_d["telemetry"])
    assert drops, "dropless must still sow moe_drop_fraction"
    for leaf in drops:
        assert leaf.dtype == jnp.float32
        assert np.asarray(leaf) == 0.0

    def loss(blk):
        def f(p, xx):
            out, _ = blk.apply({"params": p}, xx,
                               mutable=["telemetry", "losses"])
            return jnp.sum(out ** 2)
        return jax.grad(f, argnums=(0, 1))(params, x)

    for a, b in zip(jax.tree.leaves(loss(d_blk)),
                    jax.tree.leaves(loss(e_blk))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_dropless_bf16_tracks_fp32():
    """bf16 compute dtype: routing stays fp32 (same decisions), output
    tracks the fp32 block to bf16 resolution."""
    ref = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                           capacity_factor=1.0, dispatch_impl="dropless")
    b16 = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                           capacity_factor=1.0, dispatch_impl="dropless",
                           dtype=jnp.bfloat16)
    x = _x(seed=11)
    params = ref.init(jax.random.PRNGKey(0), x)["params"]
    a = np.asarray(ref.apply({"params": params}, x,
                             mutable=["telemetry", "losses"])[0])
    b = np.asarray(b16.apply({"params": params}, x,
                             mutable=["telemetry", "losses"])[0],
                   np.float32)
    np.testing.assert_allclose(a, b, rtol=5e-2, atol=5e-2)


def test_dropless_expert_parallel_matches_replicated(devices):
    """Dropless under an expert x data mesh == unsharded oracle, forward
    AND grads — the sharded-operand gather miscompile guard for the
    dropless sort/combine gathers (see test_moe_sort_dispatch)."""
    block = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                             capacity_factor=1.0, dispatch_impl="dropless")
    x = _x(seed=0, b=4, t=8)
    params = block.init(jax.random.PRNGKey(0), x)["params"]

    def apply(p, xx):
        out, _ = block.apply({"params": p}, xx,
                             mutable=["telemetry", "losses"])
        return out

    def loss(p, xx):
        return jnp.sum(apply(p, xx) ** 2)

    ref = apply(params, x)
    g_ref = jax.grad(loss)(params, x)

    mesh = mesh_lib.build_mesh({"expert": 4, "data": 2})
    shardings = sharding_lib.make_shardings(params, mesh, moe_lib.EP_RULES)
    params_sharded = jax.tree.map(jax.device_put, params, shardings)
    assert "expert" in str(params_sharded["experts"]["w_up"].sharding.spec)
    with mesh_lib.use_mesh(mesh):
        out = jax.jit(apply)(params_sharded, x)
        g_out = jax.jit(jax.grad(loss))(params_sharded, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_dropless_llama_gqa_fsdp_ep(devices):
    """Full MoE-Llama (GQA trunk) one train step under fsdp x ep: the
    dropless program matches the einsum never-drop oracle loss and
    updated params through the registry -> config plumbing."""
    from pytorch_distributed_training_example_tpu.core import (
        optim, train_loop)
    from pytorch_distributed_training_example_tpu.data import prefetch
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.utils.config import Config

    mesh = mesh_lib.build_mesh({"data": 2, "fsdp": 2, "expert": 2})
    r = np.random.RandomState(0)
    toks = r.randint(0, 512, (8, 33)).astype(np.int32)
    results = {}
    for impl, cf in (("einsum", NEVER_DROP_CF), ("dropless", 1.0)):
        bundle = registry.create_model("llama_moe_tiny", seq_len=32,
                                       dtype=jnp.float32,
                                       param_dtype=jnp.float32,
                                       moe_dispatch_impl=impl,
                                       moe_capacity_factor=cf)
        tx, _ = optim.build_optimizer(
            Config(lr=1e-2, warmup_epochs=0.0, optimizer="sgd",
                   weight_decay=0.0), steps_per_epoch=10)
        rules = sharding_lib.strategy_rules("fsdp_tp", bundle.rules)
        state = train_loop.create_train_state(bundle.module, tx,
                                              bundle.input_template, mesh,
                                              rules, seed=0)
        step = jax.jit(train_loop.make_train_step(
            train_loop.get_task("lm")), donate_argnums=0)
        with mesh_lib.use_mesh(mesh):
            b = prefetch.shard_batch(
                {"tokens": toks[:, :-1], "targets": toks[:, 1:]},
                mesh_lib.batch_sharding(mesh))
            state, m = step(state, b)
        results[impl] = (float(m["loss"]),
                         np.asarray(state.params["block_0"]["moe"]
                                    ["experts"]["w_up"]))
    assert np.isfinite(results["dropless"][0])
    np.testing.assert_allclose(results["dropless"][0],
                               results["einsum"][0], rtol=1e-5)
    np.testing.assert_allclose(results["dropless"][1],
                               results["einsum"][1], rtol=1e-4, atol=1e-5)


def test_dropless_telemetry_drop_fraction_in_train(devices):
    """Through the real model stack the dropless drop-fraction telemetry
    is the exact fp32 constant 0.0 for every layer."""
    from pytorch_distributed_training_example_tpu.models import registry

    bundle = registry.create_model("llama_moe_tiny", seq_len=32,
                                   dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   moe_dispatch_impl="dropless")
    toks = np.random.RandomState(3).randint(0, 512, (2, 32)).astype(np.int32)
    variables = bundle.module.init(jax.random.PRNGKey(0), toks)
    _, var = bundle.module.apply({"params": variables["params"]}, toks,
                                 mutable=["telemetry", "losses"])
    drops = _drop_leaves(var["telemetry"])
    assert drops
    for leaf in drops:
        assert np.asarray(leaf) == 0.0


def test_capacity_clamp_warns_once():
    """int(cf*T*k/E) == 0 silently became capacity=1 before r14; now the
    clamp warns (once per process) for the capacity-bound impls. The
    dropless path never clamps — capacity is T*k by construction."""
    x = _x(seed=5, b=1, t=4)  # T=4, k=2, E=8, cf=0.1 -> int(0.1) == 0
    blk = moe_lib.MoEBlock(num_experts=8, ffn_dim=32, top_k=2,
                           capacity_factor=0.1, dispatch_impl="gather")
    moe_lib._capacity_clamp_warned = False
    with pytest.warns(RuntimeWarning, match="capacity clamped to 1"):
        blk.init(jax.random.PRNGKey(0), x)
    # once per process: a second trace stays silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        blk = moe_lib.MoEBlock(num_experts=8, ffn_dim=64, top_k=2,
                               capacity_factor=0.1, dispatch_impl="gather")
        blk.init(jax.random.PRNGKey(0), x)

    # dropless never routes through the clamp
    moe_lib._capacity_clamp_warned = False
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        blk = moe_lib.MoEBlock(num_experts=8, ffn_dim=32, top_k=2,
                               capacity_factor=0.1,
                               dispatch_impl="dropless")
        blk.init(jax.random.PRNGKey(0), x)


# ---- r17: expert-parallel dropless dispatch (ep_dispatch) ----------------


def _a2a_blocks_run(mesh, x, impl):
    from pytorch_distributed_training_example_tpu.ops import collectives
    from pytorch_distributed_training_example_tpu.ops import (
        pallas_compat as _compat)  # noqa: F401  jax.shard_map shim
    from jax.sharding import PartitionSpec as P

    def body(xl):
        return collectives.all_to_all_blocks(xl, "expert", impl=impl)

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P("expert"),),
                       out_specs=P("expert"), check_vma=False)
    with mesh_lib.use_mesh(mesh):
        val = jax.jit(fn)(x)
        grad = jax.jit(jax.grad(
            lambda a: jnp.sum(jnp.sin(fn(a).astype(jnp.float32)))))(x)
    return np.asarray(val), np.asarray(grad)


def test_a2a_blocks_native_vs_ppermute(devices):
    """The ppermute fallback (gloo gangs without a real all-to-all) is
    value-bitwise and grad-close to lax.all_to_all, and both match the
    block-transpose semantics: out[dst-major] = in[src-major].T."""
    ep = 4
    mesh = mesh_lib.build_mesh({"expert": ep, "data": 2})
    x = jnp.asarray(np.random.default_rng(7).standard_normal((ep * ep, 6, 8)),
                    jnp.float32)
    v_nat, g_nat = _a2a_blocks_run(mesh, x, "native")
    v_pp, g_pp = _a2a_blocks_run(mesh, x, "ppermute")
    np.testing.assert_array_equal(v_nat, v_pp)
    np.testing.assert_allclose(g_nat, g_pp, rtol=1e-6, atol=1e-7)
    # semantics: device p's block q lands on device q as its block p
    blocks = np.asarray(x).reshape(ep, ep, 6, 8)
    np.testing.assert_array_equal(
        v_nat, np.swapaxes(blocks, 0, 1).reshape(ep * ep, 6, 8))
    # grad of sum-of-sin is elementwise through a permutation: positions
    # only move, so the cotangent must ride the inverse route exactly
    np.testing.assert_allclose(g_nat, np.cos(np.asarray(x)), rtol=1e-6,
                               atol=1e-7)


@pytest.mark.parametrize("ep_dispatch,chunks", [
    ("a2a", 2),
    ("a2a_overlap", 2),     # even split: R=16 -> [8, 8]
    ("a2a_overlap", 3),     # torn last window: R=16 -> [6, 6, 4]
    ("a2a_overlap", 16),    # chunk == single row (degenerate geometry)
])
def test_dropless_ep_dispatch_matches_replicated(devices, ep_dispatch,
                                                 chunks):
    """Sharded EP execution (a2a tokens to weight shards, local gmm) ==
    the replicated r14 block, forward and grads, including the torn
    ragged-last-chunk double-buffer geometries. Tolerance is the
    block-level contract (PROFILE_MOE.md r17): the gmm itself is bitwise,
    the surrounding router matmul fuses differently once the mesh is
    live, giving 1-ulp-scale wobble."""
    blk_kw = dict(num_experts=4, ffn_dim=32, top_k=2, capacity_factor=1.0,
                  dispatch_impl="dropless")
    ref_blk = moe_lib.MoEBlock(**blk_kw)
    ep_blk = moe_lib.MoEBlock(**blk_kw, ep_dispatch=ep_dispatch,
                              ep_overlap_chunks=chunks)
    x = _x(seed=3, b=2, t=16)  # kT=64, ep=4 -> R=16 rows per device
    params = ref_blk.init(jax.random.PRNGKey(0), x)["params"]

    def apply(blk, p, xx):
        out, _ = blk.apply({"params": p}, xx,
                           mutable=["telemetry", "losses"])
        return out

    ref = apply(ref_blk, params, x)
    g_ref = jax.grad(lambda p: jnp.sum(apply(ref_blk, p, x) ** 2))(params)

    mesh = mesh_lib.build_mesh({"expert": 4, "data": 2})
    shardings = sharding_lib.make_shardings(params, mesh, moe_lib.EP_RULES)
    p_sh = jax.tree.map(jax.device_put, params, shardings)
    with mesh_lib.use_mesh(mesh):
        out = jax.jit(lambda p: apply(ep_blk, p, x))(p_sh)
        g_out = jax.jit(jax.grad(
            lambda p: jnp.sum(apply(ep_blk, p, x) ** 2)))(p_sh)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ep_a2a_impl_env_ppermute_end_to_end(devices, monkeypatch):
    """PDTX_EP_A2A_IMPL=ppermute swaps the transport under the whole
    block: outputs must match the native-a2a run bitwise (same floats,
    different collective)."""
    blk = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                           capacity_factor=1.0, dispatch_impl="dropless",
                           ep_dispatch="a2a")
    x = _x(seed=9, b=2, t=16)
    params = blk.init(jax.random.PRNGKey(1), x)["params"]
    mesh = mesh_lib.build_mesh({"expert": 4, "data": 2})
    shardings = sharding_lib.make_shardings(params, mesh, moe_lib.EP_RULES)
    p_sh = jax.tree.map(jax.device_put, params, shardings)

    def run():
        with mesh_lib.use_mesh(mesh):
            out, _ = jax.jit(lambda p: blk.apply(
                {"params": p}, x, mutable=["telemetry", "losses"]))(p_sh)
        return np.asarray(out)

    monkeypatch.setenv(moe_lib.EP_A2A_IMPL_ENV, "native")
    a = run()
    monkeypatch.setenv(moe_lib.EP_A2A_IMPL_ENV, "ppermute")
    jax.clear_caches()  # env is read at trace time
    b = run()
    np.testing.assert_array_equal(a, b)


def test_ep_chunk_log_static_and_deterministic(devices, tmp_path,
                                               monkeypatch):
    """The a2a chunk log captures the static transfer geometry (torn last
    chunk included) and is byte-identical across traces — the dryrun
    gang's determinism contract."""
    log = tmp_path / "chunks.jsonl"
    monkeypatch.setenv(moe_lib.A2A_CHUNK_LOG_ENV, str(log))
    blk = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                           capacity_factor=1.0, dispatch_impl="dropless",
                           ep_dispatch="a2a_overlap", ep_overlap_chunks=3)
    x = _x(seed=4, b=2, t=16)  # R=16 -> chunk_rows [6, 6, 4]
    params = blk.init(jax.random.PRNGKey(0), x)["params"]
    mesh = mesh_lib.build_mesh({"expert": 4, "data": 2})

    def trace():
        with mesh_lib.use_mesh(mesh):
            jax.jit(lambda p: blk.apply(
                {"params": p}, x,
                mutable=["telemetry", "losses"])[0]).lower(params)

    trace()
    first = log.read_text()
    trace()
    lines = log.read_text().splitlines()
    assert len(lines) == 2 and lines[0] == lines[1], lines
    assert first.splitlines()[0] == lines[0]
    import json as _json
    row = _json.loads(lines[0])
    assert row["mode"] == "a2a_overlap" and row["ep"] == 4
    assert row["chunk_rows"] == [6, 6, 4] and row["rows_per_device"] == 16
    assert row["send_bytes_per_chunk"] == [4 * w * D * 4
                                           for w in (6, 6, 4)]


def test_ep_overlap_hlo_interleaves_a2a_with_gmm(devices):
    """Acceptance criterion: the a2a_overlap compiled program actually
    interleaves per-chunk all-to-all transfers with grouped-FFN compute —
    inspected on the optimized HLO. The plain a2a variant moves the same
    tokens in ONE all-to-all; overlap splits it into >= n_chunks of them,
    and at least one moe_experts_gmm computation sits strictly between
    the first and last transfer in program order."""
    import re as _re

    x = _x(seed=2, b=2, t=16)
    mesh = mesh_lib.build_mesh({"expert": 4, "data": 2})

    def hlo(ep_dispatch, chunks=3):
        blk = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                               capacity_factor=1.0,
                               dispatch_impl="dropless",
                               ep_dispatch=ep_dispatch,
                               ep_overlap_chunks=chunks)
        params = blk.init(jax.random.PRNGKey(0), x)["params"]
        shardings = sharding_lib.make_shardings(params, mesh,
                                                moe_lib.EP_RULES)
        p_sh = jax.tree.map(jax.device_put, params, shardings)
        with mesh_lib.use_mesh(mesh):
            return jax.jit(lambda p: blk.apply(
                {"params": p}, x, mutable=["telemetry", "losses"]
            )[0]).lower(p_sh).compile().as_text()

    a2a_re = _re.compile(r"= (?:\([^)]*\)|\S+) all-to-all(?:-start)?\(")
    n_plain = len(a2a_re.findall(hlo("a2a")))
    text = hlo("a2a_overlap", chunks=3)
    lines = text.splitlines()
    a2a_at = [i for i, ln in enumerate(lines) if a2a_re.search(ln)]
    gmm_at = [i for i, ln in enumerate(lines)
              if "moe_experts_gmm" in ln and "fusion" in ln]
    assert n_plain >= 1 and len(a2a_at) >= 3 * n_plain, (n_plain, len(a2a_at))
    assert gmm_at, "grouped-FFN fusions must be scope-tagged in the HLO"
    assert any(a2a_at[0] < g < a2a_at[-1] for g in gmm_at), (
        "no gmm compute between the first and last a2a chunk",
        a2a_at[:4], gmm_at[:4])
