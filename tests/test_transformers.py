"""Transformer model family: shapes, TP/FSDP-TP equivalence, remat, CP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.core import optim, train_loop
from pytorch_distributed_training_example_tpu.data import prefetch
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
from pytorch_distributed_training_example_tpu.utils.config import Config

SEQ = 64


def _lm_batch(n=8, seed=0, vocab=512):
    r = np.random.RandomState(seed)
    toks = r.randint(0, vocab, (n, SEQ + 1)).astype(np.int32)
    return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


def _build(model_name, mesh, strategy, seq_len=SEQ, **model_kw):
    # SGD for the equivalence oracle: Adam's per-element normalization turns
    # benign reduction-order noise (~1e-6) on near-zero grads into full-lr
    # sign flips, which is a property of Adam, not of the sharding.
    cfg = Config(lr=1e-2, warmup_epochs=0.0, optimizer="sgd", grad_clip=0.0,
                 weight_decay=0.0)
    bundle = registry.create_model(model_name, seq_len=seq_len,
                                   dtype=jnp.float32, param_dtype=jnp.float32,
                                   sp=strategy.endswith("_sp"), **model_kw)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
    rules = sharding_lib.strategy_rules(strategy, bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    task = train_loop.get_task(bundle.task)
    step = jax.jit(train_loop.make_train_step(task), donate_argnums=0)
    return state, step


def _run(model_name, mesh, strategy, n_steps=2, **model_kw):
    state, step = _build(model_name, mesh, strategy, **model_kw)
    with mesh_lib.use_mesh(mesh):
        sh = mesh_lib.batch_sharding(mesh)
        for i in range(n_steps):
            batch = prefetch.shard_batch(_lm_batch(seed=i), sh)
            state, metrics = step(state, batch)
        params = jax.device_get(state.params)
    return params, {k: float(v) for k, v in metrics.items()}


@pytest.mark.parametrize("model_name", ["gpt2_tiny", "llama_tiny"])
@pytest.mark.parametrize("mesh_cfg,strategy", [
    ({"data": 2, "model": 4}, "fsdp_tp"),
    ({"data": 2, "fsdp": 2, "model": 2}, "fsdp_tp"),
])
def test_tp_matches_single_device(devices, model_name, mesh_cfg, strategy):
    ref_params, ref_m = _run(model_name, mesh_lib.single_device_mesh(), "dp")
    par_params, par_m = _run(model_name, mesh_lib.build_mesh(mesh_cfg), strategy)
    assert np.isclose(ref_m["loss"], par_m["loss"], rtol=1e-3), (ref_m, par_m)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(par_params)):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_tp_actually_shards(devices):
    mesh = mesh_lib.build_mesh({"data": 2, "model": 4})
    state, _ = _build("llama_tiny", mesh, "fsdp_tp")
    shardings = {
        sharding_lib.param_path(p): leaf.sharding.spec
        for p, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    }
    qk = [s for p, s in shardings.items() if "query/kernel" in p]
    assert qk and all("model" in str(s) for s in qk), shardings


def test_context_parallel_train_step(devices):
    """Ring attention engages via mesh shape alone (context axis > 1)."""
    mesh = mesh_lib.build_mesh({"data": 2, "context": 4})
    ref_params, ref_m = _run("llama_tiny", mesh_lib.single_device_mesh(), "dp")
    par_params, par_m = _run("llama_tiny", mesh, "dp")
    assert np.isclose(ref_m["loss"], par_m["loss"], rtol=1e-3)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(par_params)):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_composed_3d_mesh_train_step(devices):
    """The composed mesh: dp x ep x seq on one MoE model, one train step
    program — parity vs the single-device oracle (ROADMAP item 4)."""
    # Dropless dispatch: capacity-dropped routing is discontinuous at the
    # capacity boundary, so reduction-order noise across meshes can flip a
    # drop and break parity — a property of capacity factors, not of the
    # composed mesh.
    mesh = mesh_lib.build_mesh({"data": 2, "expert": 2, "seq": 2})
    ref_params, ref_m = _run("llama_moe_tiny", mesh_lib.single_device_mesh(),
                             "dp", moe_dispatch_impl="dropless")
    par_params, par_m = _run("llama_moe_tiny", mesh, "fsdp_tp",
                             moe_dispatch_impl="dropless")
    assert np.isclose(ref_m["loss"], par_m["loss"], rtol=1e-3), (ref_m, par_m)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(par_params)):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_composed_seq_tp_train_step(devices):
    """dp x seq x tp on the dense model: ring attention over 'context'
    composed with Megatron column/row splits over 'model'."""
    mesh = mesh_lib.build_mesh({"data": 2, "seq": 2, "model": 2})
    ref_params, ref_m = _run("gpt2_tiny", mesh_lib.single_device_mesh(), "dp")
    par_params, par_m = _run("gpt2_tiny", mesh, "fsdp_tp")
    assert np.isclose(ref_m["loss"], par_m["loss"], rtol=1e-3), (ref_m, par_m)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(par_params)):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_wpe_shards_over_context(devices):
    """gpt2's position embedding (the one seq-dim param) shards over the
    context axis — SNIPPETS.md [3]'s '"seq": None' TODO, filled."""
    mesh = mesh_lib.build_mesh({"data": 2, "context": 4})
    state, _ = _build("gpt2_tiny", mesh, "fsdp_tp")
    specs = {
        sharding_lib.param_path(p): leaf.sharding.spec
        for p, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    }
    wpe = [s for p, s in specs.items() if "wpe" in p]
    assert wpe and all("context" in str(s) for s in wpe), specs


def test_seq_rules_cover_constrain_sites():
    """The shared activation table carries the sequence dim on 'context' in
    every entry, and folds 'model' in only under SP."""
    from jax.sharding import PartitionSpec as P

    rules = sharding_lib.seq_rules()
    assert set(rules) == {"residual", "qkv", "ffn_hidden", "logits"}
    assert rules["residual"] == P(mesh_lib.BATCH_AXES, "context", None)
    sp = sharding_lib.seq_rules(sp=True)
    assert sp["residual"] == P(mesh_lib.BATCH_AXES, ("context", "model"), None)
    # Matmul-region entries keep 'model' on the hidden/head dim regardless.
    assert sp["qkv"] == rules["qkv"]


def test_ulysses_end_to_end_train_step(devices):
    """Ulysses (all-to-all seq<->head) as the CP implementation of a full
    train step, selected the way a user would: attn_impl='ulysses'."""
    mesh = mesh_lib.build_mesh({"data": 2, "context": 4})
    cfg = Config(lr=1e-2, warmup_epochs=0.0, optimizer="sgd", grad_clip=0.0,
                 weight_decay=0.0)
    # llama_tiny: 4 q-heads / 2 kv-heads over 4 context shards (GQA broadcast
    # path inside ulysses_attention).
    bundle = registry.create_model("llama_tiny", seq_len=SEQ,
                                   dtype=jnp.float32, param_dtype=jnp.float32,
                                   attn_impl="ulysses")
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")),
                   donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        sh = mesh_lib.batch_sharding(mesh)
        for i in range(2):
            state, m = step(state, prefetch.shard_batch(_lm_batch(seed=i), sh))
        params = jax.device_get(state.params)
    # oracle: same run on one device with plain attention
    ref_params, ref_m = _run("llama_tiny", mesh_lib.single_device_mesh(), "dp")
    assert np.isclose(ref_m["loss"], float(m["loss"]), rtol=1e-3)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(params)):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_sp_matches_non_sp(devices):
    """Megatron SP is a resharding of activations, not a different program:
    loss/params must match the plain TP run exactly (SURVEY.md §2c SP)."""
    mesh = mesh_lib.build_mesh({"data": 2, "model": 4})
    ref_params, ref_m = _run("llama_tiny", mesh, "fsdp_tp")
    sp_params, sp_m = _run("llama_tiny", mesh, "fsdp_tp_sp")
    assert np.isclose(ref_m["loss"], sp_m["loss"], rtol=1e-4), (ref_m, sp_m)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(sp_params)):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-4)


def test_sp_reduces_activation_memory(devices):
    """The point of SP: residual-stream activations between matmul regions
    shard over the TP axis -> per-device temp memory drops."""
    mesh = mesh_lib.build_mesh({"model": 8})
    seq = 256

    def temp_bytes(strategy):
        state, step = _build("llama_tiny", mesh, strategy, seq_len=seq)
        r = np.random.RandomState(0)
        toks = r.randint(0, 512, (8, seq + 1)).astype(np.int32)
        with mesh_lib.use_mesh(mesh):
            batch = prefetch.shard_batch(
                {"tokens": toks[:, :-1], "targets": toks[:, 1:]},
                mesh_lib.batch_sharding(mesh))
            compiled = step.lower(state, batch).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    plain, sp = temp_bytes("fsdp_tp"), temp_bytes("fsdp_tp_sp")
    assert sp < plain * 0.9, (sp, plain)


def test_remat_matches_no_remat(devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    bundle = registry.create_model("llama_tiny", seq_len=SEQ,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    bundle_r = registry.create_model("llama_tiny", seq_len=SEQ,
                                     dtype=jnp.float32, param_dtype=jnp.float32,
                                     remat=True)
    cfg = Config(lr=1e-2, warmup_epochs=0.0, optimizer="adamw")
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    s1 = train_loop.create_train_state(bundle.module, tx, bundle.input_template,
                                       mesh, rules, seed=0)
    s2 = train_loop.create_train_state(bundle_r.module, tx, bundle.input_template,
                                       mesh, rules, seed=0)
    task = train_loop.get_task("lm")
    step = jax.jit(train_loop.make_train_step(task), donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        b = prefetch.shard_batch(_lm_batch(), mesh_lib.batch_sharding(mesh))
        _, m1 = step(s1, b)
        b = prefetch.shard_batch(_lm_batch(), mesh_lib.batch_sharding(mesh))
        _, m2 = step(s2, b)
    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)


def test_vit_tp_matches_single_device(devices):
    """ViT family TP rules: fsdp_tp equals the single-device oracle."""
    def run(mesh, strategy):
        cfg = Config(lr=1e-2, warmup_epochs=0.0, optimizer="sgd",
                     grad_clip=0.0, weight_decay=0.0)
        bundle = registry.create_model("vit_tiny", num_classes=10,
                                       image_size=32, dtype=jnp.float32,
                                       param_dtype=jnp.float32)
        tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
        rules = sharding_lib.strategy_rules(strategy, bundle.rules)
        state = train_loop.create_train_state(
            bundle.module, tx, bundle.input_template, mesh, rules, seed=0)
        step = jax.jit(train_loop.make_train_step(
            train_loop.get_task(bundle.task)), donate_argnums=0)
        r = np.random.RandomState(0)
        b = {"image": r.randn(16, 32, 32, 3).astype(np.float32),
             "label": (np.arange(16) % 10).astype(np.int32)}
        with mesh_lib.use_mesh(mesh):
            state, m = step(state, prefetch.shard_batch(
                b, mesh_lib.batch_sharding(mesh)))
            return jax.device_get(state.params), float(m["loss"])

    ref_params, ref_loss = run(mesh_lib.single_device_mesh(), "dp")
    par_params, par_loss = run(mesh_lib.build_mesh({"data": 2, "model": 4}),
                               "fsdp_tp")
    assert np.isclose(ref_loss, par_loss, rtol=1e-3)
    for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(par_params)):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


def test_vit_train_step(devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    cfg = Config(lr=1e-3, optimizer="adamw")
    bundle = registry.create_model("vit_tiny", num_classes=10, image_size=32,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    task = train_loop.get_task(bundle.task)
    step = jax.jit(train_loop.make_train_step(task), donate_argnums=0)
    r = np.random.RandomState(0)
    batch = {"image": r.randn(16, 32, 32, 3).astype(np.float32),
             "label": (np.arange(16) % 10).astype(np.int32)}
    with mesh_lib.use_mesh(mesh):
        b = prefetch.shard_batch(batch, mesh_lib.batch_sharding(mesh))
        state, m = step(state, b)
    assert np.isfinite(m["loss"])


def test_gpt2_param_count():
    from pytorch_distributed_training_example_tpu.models import gpt2

    assert abs(gpt2.num_params(gpt2.gpt2_124m()) - 124.4e6) < 1e6


def test_scan_layers_runs_with_tp_rules(devices):
    """nn.scan-stacked Llama trains; stacked params get rank-shifted TP specs."""
    from pytorch_distributed_training_example_tpu.models import llama

    mesh = mesh_lib.build_mesh({"model": 2, "fsdp": 2, "data": 2})
    module = llama.llama_tiny(scan_layers=True, num_layers=3)
    cfg = Config(lr=1e-2, warmup_epochs=0.0)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=10)
    state = train_loop.create_train_state(
        module, tx, (jnp.zeros((2, SEQ), jnp.int32),), mesh,
        llama.TP_RULES, seed=0)
    qk = state.params["blocks"]["block"]["attn"]["query"]["kernel"]
    assert qk.ndim == 4 and "model" in str(qk.sharding.spec)
    step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")),
                   donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        b = prefetch.shard_batch(_lm_batch(), mesh_lib.batch_sharding(mesh))
        state, m = step(state, b)
    assert np.isfinite(float(m["loss"]))
