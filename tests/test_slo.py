"""Serving SLO observability (serve/slo.py + the r20 consumers).

Three layers under test, mirroring the module split:

- math: ``quantile`` against the numpy reference, sliding-window eviction,
  attainment accounting, episode-gated breach detection — all stdlib,
  clock-injected, jax-free;
- artifacts: the request-trace ring (drop counting, rotation caps,
  otherData-first torn-write contract), atomic ``slo.jsonl`` flush, the
  ``check_regression --slo`` gate, and ``trace_merge`` folding reqtrace
  files into the fleet trace;
- consumers: MetricsServer histogram rendering under concurrent scrapes,
  the fleet scheduler's quantized SLO placement weight (byte-reproducible
  plans), and — the one jax test — the zero-intrusion contract on the real
  engine: tracing ON changes neither tokens nor compile count.
"""

import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.serve import slo as slo_lib
from pytorch_distributed_training_example_tpu.utils import fleetobs
from pytorch_distributed_training_example_tpu.utils import (
    scheduler as scheduler_lib)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import check_regression  # noqa: E402
import trace_merge  # noqa: E402

RUN = "run-slo-test"


# ---------------------------------------------------------------------------
# quantile + window math vs the numpy reference
# ---------------------------------------------------------------------------


def test_quantile_matches_numpy():
    rng = np.random.default_rng(3)
    for n in (1, 2, 3, 7, 50, 256):
        xs = rng.standard_normal(n).tolist()
        for q in (0, 25, 50, 90, 99, 100):
            assert slo_lib.quantile(xs, q) == pytest.approx(
                float(np.percentile(np.asarray(xs), q)), abs=1e-12), (n, q)


def test_quantile_degenerate_inputs():
    assert slo_lib.quantile([], 50) is None
    assert slo_lib.quantile([4.25], 99) == 4.25


def test_window_eviction_keeps_current_regime():
    """Sample-count sliding window: after heavy eviction the quantiles
    describe only the most recent ``window`` samples."""
    t = slo_lib.SLOTracker(window=8)
    for i in range(100):  # 92 evicted; survivors are 92..99 ms
        t.observe_itl("r0", "both", i * 1e-3)
    snap = t.snapshot()["r0/both"]
    tail = np.arange(92, 100, dtype=np.float64)
    assert snap["itl_count"] == 8
    assert snap["itl_p50_ms"] == pytest.approx(float(np.percentile(tail, 50)))
    assert snap["itl_p99_ms"] == pytest.approx(float(np.percentile(tail, 99)))
    assert t.snapshot()["r0/both"]["ttft_count"] == 0


def test_attainment_counts_in_target_fraction():
    t = slo_lib.SLOTracker(window=16, ttft_target_ms=100.0,
                           itl_target_ms=10.0)
    for ms in (50, 150):  # one TTFT in target, one out
        t.observe_ttft("r0", "both", ms * 1e-3)
    for ms in (5, 5, 5, 50):  # three ITL in target, one out
        t.observe_itl("r0", "both", ms * 1e-3)
    assert t.snapshot()["r0/both"]["attainment"] == pytest.approx(4 / 6)
    assert t.overall_attainment() == pytest.approx(4 / 6)
    # No targets -> everything counts as attained.
    free = slo_lib.SLOTracker(window=16)
    free.observe_ttft("r0", "both", 10.0)
    assert free.overall_attainment() == 1.0
    # No samples at all -> vacuous 1.0 (the scheduler's neutral weight).
    assert slo_lib.SLOTracker(window=4).overall_attainment() == 1.0


def test_breach_is_episode_gated():
    t = slo_lib.SLOTracker(window=4, itl_target_ms=10.0,
                           min_breach_samples=4, clock=lambda: 0.0)
    for _ in range(3):  # below min_breach_samples: never fires
        t.observe_itl("r0", "both", 0.050)
        assert t.breach() is None
    t.observe_itl("r0", "both", 0.050)
    reason = t.breach()
    assert reason is not None and "r0/both:itl_p99" in reason
    assert t.breach() is None  # same episode stays quiet
    for _ in range(4):  # window recovers -> episode re-arms
        t.observe_itl("r0", "both", 0.001)
    assert t.breach() is None
    for _ in range(4):
        t.observe_itl("r0", "both", 0.050)
    assert t.breach() is not None
    assert t.breaches == 2


# ---------------------------------------------------------------------------
# RequestTrace ring: drops, rotation cap, otherData-first salvage contract
# ---------------------------------------------------------------------------


def _fixed_clocks():
    return dict(clock=lambda: 12.0, wall_clock=lambda: 1000.0)


def test_request_trace_ring_counts_drops():
    rt = slo_lib.RequestTrace("replica0", run_id=RUN, capacity=4,
                              **_fixed_clocks())
    for i in range(7):
        rt.instant(f"e{i}", t=12.0 + i)
    assert rt.dropped_spans == 3 and rt.pending == 4
    names = [e["name"] for e in rt.trace_events()["traceEvents"]]
    assert names == ["e3", "e4", "e5", "e6"]  # oldest evicted first
    assert rt.trace_events()["otherData"]["dropped_spans"] == 3


def test_request_trace_rotation_caps_generations(tmp_path):
    rt = slo_lib.RequestTrace("replica0", run_id=RUN, capacity=8,
                              max_generations=2, **_fixed_clocks())
    d = str(tmp_path)
    for gen in range(4):
        rt.span("work", 12.0, 12.001, request_id=f"g{gen}")
        rt.rotate(d)
        assert rt.pending == 0  # rotation clears the ring
    names = sorted(n for n in os.listdir(d) if n.startswith("reqtrace."))
    # Generations 0 and 1 were unlinked by the max_generations=2 cap.
    assert names == ["reqtrace.replica0.a1.g2.json",
                     "reqtrace.replica0.a1.g3.json"]
    rt.instant("tail", t=12.5)
    final = rt.write(d)
    assert os.path.basename(final) == "reqtrace.replica0.a1.json"
    # Torn-write salvage contract: otherData must be the FIRST key so a
    # truncated file keeps its header (trace_merge.load_trace_salvage).
    raw = open(final).read()
    assert raw.index('"otherData"') < raw.index('"traceEvents"')
    assert trace_merge.load_trace_salvage(final)["otherData"]["run_id"] == RUN


def test_request_trace_role_lanes():
    rt = slo_lib.RequestTrace("replica0", run_id=RUN, **_fixed_clocks())
    rt.instant("admit", t=12.0, role="prefill")
    rt.span("decode_step", 12.0, 12.001, role="decode")
    rt.instant("router_admit", t=12.0, role="router")
    tids = {e["name"]: e["tid"] for e in rt.trace_events()["traceEvents"]}
    assert tids == {"admit": slo_lib.ROLE_TIDS["prefill"],
                    "decode_step": slo_lib.ROLE_TIDS["decode"],
                    "router_admit": slo_lib.ROLE_TIDS["router"]}


# ---------------------------------------------------------------------------
# slo.jsonl: flush atomicity surface + the check_regression --slo gate
# ---------------------------------------------------------------------------


def _sampled_tracker():
    t = slo_lib.SLOTracker(window=8, ttft_target_ms=100.0, itl_target_ms=10.0)
    for i in range(12):
        t.observe_ttft("replica0", "both", 0.020 + i * 1e-3)
        t.observe_itl("replica0", "both", 0.004)
    t.observe_itl("replica1", "both", 0.002)
    return t


def test_flush_and_gate_round_trip(tmp_path):
    t = _sampled_tracker()
    path = t.flush(str(tmp_path), RUN, dropped_spans=2)
    rows = [json.loads(line) for line in open(path)]
    assert rows[0]["kind"] == "slo_header" and rows[0]["window"] == 8
    assert rows[-1]["kind"] == "slo_summary"
    assert rows[-1]["dropped_spans"] == 2
    assert {r["kind"] for r in rows[1:-1]} == {"slo_window"}
    failures, report = check_regression.check_slo(path)
    assert not failures, report
    assert any(line.startswith("OK slo") for line in report)
    # The scheduler-side reader agrees with the summary row.
    assert fleetobs.read_slo_attainment(path) == rows[-1]["attainment"]


@pytest.mark.parametrize("mutate, expect", [
    (lambda rows: rows[1:], "slo_header"),              # missing header
    (lambda rows: [rows[0], rows[-1]], "no slo_window"),
    (lambda rows: [dict(r, run_id="other") if r["kind"] == "slo_summary"
                   else r for r in rows], "run ids"),
    (lambda rows: [dict(r, ttft_p99_ms=float("nan"))
                   if r["kind"] == "slo_window" else r
                   for r in rows], "non-finite"),
    (lambda rows: [dict(r, itl_count=999) if r["kind"] == "slo_window"
                   else r for r in rows], "coverage"),
    (lambda rows: rows + [rows[-1]], "slo_summary"),    # duplicate summary
])
def test_gate_rejects_malformed_slo(tmp_path, mutate, expect):
    rows = _sampled_tracker().rows(RUN)
    path = os.path.join(str(tmp_path), "slo.jsonl")
    with open(path, "w") as fh:
        for row in mutate(rows):
            fh.write(json.dumps(row) + "\n")
    failures, _ = check_regression.check_slo(path)
    assert failures and expect in failures[0], failures


def test_read_slo_attainment_is_tolerant(tmp_path):
    assert fleetobs.read_slo_attainment(
        os.path.join(str(tmp_path), "absent.jsonl")) is None
    path = os.path.join(str(tmp_path), "slo.jsonl")
    with open(path, "w") as fh:
        fh.write("not json at all\n")
        fh.write(json.dumps({"kind": "slo_summary", "attainment": 7.0}) + "\n")
    assert fleetobs.read_slo_attainment(path) == 1.0  # clamped into [0, 1]


# ---------------------------------------------------------------------------
# MetricsServer: histogram rendering + concurrent scrape safety
# ---------------------------------------------------------------------------


def test_metrics_server_histogram_rendering():
    srv = fleetobs.MetricsServer(port=0, addr="127.0.0.1").start()
    try:
        t = _sampled_tracker()
        srv.update(**t.gauges(extra_dropped=1))
        srv.update_histograms(**t.histograms())
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read().decode()
        assert "pdtx_serve_slo_attainment" in text
        assert "pdtx_serve_slo_dropped_spans 1.0" in text
        assert ("# TYPE pdtx_serve_slo_ttft_ms_replica0_both histogram"
                in text)
        assert 'pdtx_serve_slo_ttft_ms_replica0_both_bucket{le="+Inf"} 12' \
            in text
        assert "pdtx_serve_slo_ttft_ms_replica0_both_count 12" in text
        assert "pdtx_serve_slo_ttft_ms_replica0_both_sum" in text
    finally:
        srv.stop()


def test_metrics_server_concurrent_scrapes_during_updates():
    """N writer threads hammer gauges + histograms while M readers scrape
    /metrics — every response must parse cleanly (no torn renders, no
    server-thread exceptions)."""
    srv = fleetobs.MetricsServer(port=0, addr="127.0.0.1").start()
    errors: list[Exception] = []
    stop = threading.Event()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def writer(seed):
            t = slo_lib.SLOTracker(window=32, ttft_target_ms=50.0)
            i = 0
            while not stop.is_set():
                t.observe_ttft(f"r{seed}", "both", (i % 40) * 1e-3)
                try:
                    srv.update(**t.gauges())
                    srv.update_histograms(**t.histograms())
                except Exception as e:  # pragma: no cover - failure path
                    errors.append(e)
                    return
                i += 1

        def reader():
            while not stop.is_set():
                try:
                    text = urllib.request.urlopen(
                        f"{base}/metrics", timeout=5).read().decode()
                    for line in text.splitlines():
                        assert line.startswith(("#", "pdtx_")), line
                except Exception as e:  # pragma: no cover - failure path
                    errors.append(e)
                    return

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(3)]
        threads += [threading.Thread(target=reader) for _ in range(4)]
        for th in threads:
            th.start()
        # Let them contend for a fixed number of scrapes' worth of time.
        for _ in range(25):
            urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
        stop.set()
        for th in threads:
            th.join(timeout=10)
        assert not errors, errors
    finally:
        stop.set()
        srv.stop()


def test_straggler_gauges_shape():
    rows = [
        {"step": 1, "flagged": False},
        {"step": 2, "flagged": True, "slowest_rank": 1, "cause": "input_wait",
         "delta_s": 0.5},
        {"step": 3, "flagged": True, "slowest_rank": 1, "cause": "compute",
         "delta_s": 1.25},
    ]
    g = fleetobs.straggler_gauges(rows, prefix="fleet_straggler_job0")
    assert g["fleet_straggler_job0_steps"] == 3.0
    assert g["fleet_straggler_job0_flagged_total"] == 2.0
    assert g["fleet_straggler_job0_flagged_rank1"] == 2.0
    assert g["fleet_straggler_job0_cause_input_wait"] == 1.0
    assert g["fleet_straggler_job0_worst_delta_s"] == 1.25
    # Quiet fleet: no worst-delta gauge, zero flags.
    quiet = fleetobs.straggler_gauges([{"step": 1, "flagged": False}])
    assert quiet["fleet_straggler_flagged_total"] == 0.0
    assert "fleet_straggler_worst_delta_s" not in quiet


# ---------------------------------------------------------------------------
# Fleet scheduler: quantized SLO attainment in the placement weight
# ---------------------------------------------------------------------------


def _fleet(tmp_path, attainment):
    ckdir = os.path.join(str(tmp_path), "srv_ck")
    os.makedirs(ckdir, exist_ok=True)
    if attainment is not None:
        t = slo_lib.SLOTracker(window=8, itl_target_ms=10.0)
        n_ok = round(attainment * 8)
        for i in range(8):
            t.observe_itl("r0", "both", 0.001 if i < n_ok else 0.100)
        t.flush(ckdir, RUN)
    doc = {"pool": 8, "jobs": [
        {"name": "train", "cmd": ["x"], "world": "2:8", "priority": 1},
        {"name": "srv", "cmd": ["x", "--checkpoint-dir", ckdir],
         "world": "2:8", "priority": 1, "kind": "serve"},
    ]}
    path = os.path.join(str(tmp_path), "jobs.json")
    with open(path, "w") as fh:
        json.dump(doc, fh)
    pool, specs = scheduler_lib.load_jobs(path)
    return scheduler_lib.FleetScheduler(pool, specs)


def test_scheduler_degraded_serve_job_loses_devices(tmp_path):
    """D'Hondt with the SLO factor: a serve job attaining 50% gets fewer
    devices than the equal-priority trainer; a healthy one splits evenly."""
    healthy = _fleet(tmp_path / "a", 1.0)
    worlds = {d["job"]: d["world"] for d in healthy.plan(0.0)}
    assert worlds["train"] == worlds["srv"] == 4
    assert healthy.state("srv").slo_attainment == 1.0

    degraded = _fleet(tmp_path / "b", 0.5)
    worlds = {d["job"]: d["world"] for d in degraded.plan(0.0)}
    assert degraded.state("srv").slo_attainment == 0.5
    assert worlds["train"] > worlds["srv"] >= 2
    assert "fleet_job_slo_attainment_srv" in degraded.gauges()


def test_scheduler_plan_byte_reproducible_with_slo(tmp_path):
    a = _fleet(tmp_path / "x", 0.7).plan(0.0)
    b = _fleet(tmp_path / "x", 0.7).plan(0.0)  # same dir, same slo.jsonl
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_scheduler_ignores_missing_or_stale_slo(tmp_path):
    sched = _fleet(tmp_path, None)  # no slo.jsonl at all
    sched.plan(0.0)
    assert sched.state("srv").slo_attainment == 1.0  # neutral default


# ---------------------------------------------------------------------------
# trace_merge: reqtrace files join the fleet trace as serve track groups
# ---------------------------------------------------------------------------


def _write_rank_trace(d, run_id=RUN):
    doc = {"otherData": {"schema_version": fleetobs.SCHEMA_VERSION,
                         "run_id": run_id, "host": "hostA", "rank": 0,
                         "clock_anchor": {"wall": 1000.0, "monotonic": 0.0}},
           "displayTimeUnit": "ms",
           "traceEvents": [{"name": "step", "cat": "span", "ph": "X",
                            "ts": 100, "dur": 800, "pid": 0, "tid": 1}]}
    with open(os.path.join(d, "trace_events.r0.a1.json"), "w") as fh:
        json.dump(doc, fh)


def _write_reqtrace(d, replica, *, wall, run_id=RUN, rotate_first=False):
    rt = slo_lib.RequestTrace(replica, run_id=run_id, capacity=16,
                              clock=lambda: 0.0, wall_clock=lambda: wall)
    if rotate_first:
        rt.span("decode_step", 0.0, 0.001, role="decode")
        rt.rotate(d)
    rt.span("request", 0.0, 0.010, request_id="r1")
    rt.instant("router_admit", t=0.0, role="router", request_id="r1")
    rt.write(d)


def test_trace_merge_folds_reqtraces_into_fleet_trace(tmp_path):
    d = str(tmp_path)
    _write_rank_trace(d)
    _write_reqtrace(d, "replica0", wall=1000.0, rotate_first=True)
    _write_reqtrace(d, "replica1", wall=1002.5)  # 2.5 s of wall skew
    merged = trace_merge.merge_traces(d)
    groups = merged["otherData"]["track_groups"]
    assert "hostA/rank0" in groups
    serve_groups = [g for g in groups if "/serve:" in g]
    assert len(serve_groups) == 2
    assert merged["otherData"]["run_ids"] == [RUN]
    tags = set(merged["otherData"]["merged_from"])
    assert {"r0.a1", "serve:replica0.a1", "serve:replica0.a1.g0",
            "serve:replica1.a1"} <= tags
    # Role lanes are named via thread_name metadata on the serve pids.
    lanes = {(e["pid"], e["args"]["name"])
             for e in merged["traceEvents"] if e["name"] == "thread_name"}
    for g in serve_groups:
        assert (groups[g], "router") in lanes
    # replica1's wall skew shifted its events onto the shared axis.
    by_pid = {}
    for e in merged["traceEvents"]:
        if e.get("cat") == "serve" and e["name"] == "request":
            by_pid[e["pid"]] = e["ts"]
    pid0 = groups[[g for g in serve_groups if "replica0" in g][0]]
    pid1 = groups[[g for g in serve_groups if "replica1" in g][0]]
    assert by_pid[pid1] - by_pid[pid0] == int(2.5e6)


def test_trace_merge_refuses_mixed_run_reqtrace(tmp_path):
    d = str(tmp_path)
    _write_rank_trace(d)
    _write_reqtrace(d, "replica0", wall=1000.0, run_id="other-run")
    with pytest.raises(SystemExit, match="different runs"):
        trace_merge.merge_traces(d)
    merged = trace_merge.merge_traces(d, allow_mixed_run=True)
    assert len(merged["otherData"]["run_ids"]) == 2


# ---------------------------------------------------------------------------
# The zero-intrusion contract on the real engine (the one jax test here).
# ---------------------------------------------------------------------------


def test_engine_tracing_zero_intrusion(devices):
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.serve import (
        engine as engine_lib)

    bundle = registry.create_model("llama_tiny", seq_len=128,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    module = bundle.module
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                         train=False)["params"]
    spec = engine_lib.spec_for_module(module, num_pages=32, page_size=8)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, 512, plen).tolist() for plen in (3, 8, 9, 23)]

    def run(reqtrace=None, slo=None):
        eng = engine_lib.ContinuousBatchingEngine(
            module, params, spec, decode_buckets=(1, 2, 4),
            prompt_buckets=(16, 32), max_model_len=64,
            reqtrace=reqtrace, slo=slo)
        n = eng.warmup()
        for i, prompt in enumerate(prompts):
            eng.submit(engine_lib.Request(request_id=f"r{i}", prompt=prompt,
                                          max_new_tokens=12))
        done = {r.request_id: r.generated for r in eng.run()}
        return done, eng.stats["compiles"], n

    base, base_compiles, n_exec = run()
    rt = slo_lib.RequestTrace("replica0", run_id=RUN)
    tracker = slo_lib.SLOTracker(window=64, ttft_target_ms=1e9,
                                 itl_target_ms=1e9)
    traced, traced_compiles, _ = run(reqtrace=rt, slo=tracker)

    assert traced == base                      # token identity
    assert traced_compiles == base_compiles == n_exec  # compile count flat
    events = rt.trace_events()["traceEvents"]
    names = {e["name"] for e in events}
    assert {"queue_wait", "admit", "prefill", "decode_step",
            "request"} <= names
    assert all(e.get("dur", 0) >= 0 for e in events)
    assert rt.dropped_spans == 0
    snap = tracker.snapshot()["replica0/both"]
    assert snap["ttft_count"] == 4
    assert snap["itl_count"] == sum(len(g) for g in base.values()) - 4
    assert tracker.overall_attainment() == 1.0
