"""Pipeline (GPipe/shard_map) and MoE (expert-parallel) vs their oracles."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.parallel import moe as moe_lib
from pytorch_distributed_training_example_tpu.parallel import pipeline as pp
from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib

D = 16


def _stage_fn(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def _stage_params(n_stages, seed=0):
    r = np.random.RandomState(seed)
    per = [
        {"w1": jnp.asarray(r.randn(D, 32) * 0.1, jnp.float32),
         "b1": jnp.zeros(32, jnp.float32),
         "w2": jnp.asarray(r.randn(32, D) * 0.1, jnp.float32)}
        for _ in range(n_stages)
    ]
    return pp.stack_stage_params(per)


@pytest.mark.parametrize("mesh_cfg,microbatches", [
    ({"stage": 8}, 8),
    ({"stage": 4, "data": 2}, 8),
    ({"stage": 2, "data": 2, "fsdp": 2}, 4),
])
def test_pipeline_matches_sequential(devices, mesh_cfg, microbatches):
    mesh = mesh_lib.build_mesh(mesh_cfg)
    S = mesh.shape["stage"]
    params = _stage_params(S)
    x = jnp.asarray(np.random.RandomState(1).randn(32, D), jnp.float32)
    ref = pp.sequential_apply(_stage_fn, params, x)
    out = pp.pipeline_apply(_stage_fn, params, x, mesh=mesh,
                            num_microbatches=microbatches)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match(devices):
    mesh = mesh_lib.build_mesh({"stage": 4, "data": 2})
    params = _stage_params(4)
    x = jnp.asarray(np.random.RandomState(1).randn(16, D), jnp.float32)

    g_ref = jax.grad(lambda p: pp.sequential_apply(_stage_fn, p, x).sum())(params)
    g_out = jax.grad(lambda p: pp.pipeline_apply(
        _stage_fn, p, x, mesh=mesh, num_microbatches=4).sum())(params)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_single_stage_fallback(devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    params = _stage_params(3)
    x = jnp.asarray(np.random.RandomState(1).randn(8, D), jnp.float32)
    ref = pp.sequential_apply(_stage_fn, params, x)
    out = pp.pipeline_apply(_stage_fn, params, x, mesh=mesh, num_microbatches=2)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-6)


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------


def _moe_setup(seed=0, E=4, top_k=2):
    block = moe_lib.MoEBlock(num_experts=E, ffn_dim=32, top_k=top_k,
                             capacity_factor=2.0)
    x = jnp.asarray(np.random.RandomState(seed).randn(4, 8, D), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    return block, {"params": variables["params"]}, x


def test_moe_forward_and_aux_loss():
    block, variables, x = _moe_setup()
    out, state = block.apply(variables, x, mutable=["losses"])
    assert out.shape == x.shape
    losses = state["losses"]
    (aux,) = jax.tree.leaves(losses["moe_aux_loss"])
    # raw aux is ~1 for balanced routing (>=1 by Cauchy-Schwarz), times the
    # 0.01 default weight
    assert 0.009 < float(aux) < 0.025
    (z,) = jax.tree.leaves(losses["moe_z_loss"])
    assert float(z) >= 0.0  # ST-MoE router z-loss is sown alongside


def test_moe_gather_matches_einsum_dispatch():
    """The O(E*C*d) gather dispatch must equal the O(T*E*C) GShard einsum
    formulation bit-for-bit in routing decisions (same router weights)."""
    E, k = 4, 2
    g = moe_lib.MoEBlock(num_experts=E, ffn_dim=32, top_k=k,
                         capacity_factor=1.0, dispatch_impl="gather")
    e = moe_lib.MoEBlock(num_experts=E, ffn_dim=32, top_k=k,
                         capacity_factor=1.0, dispatch_impl="einsum")
    x = jnp.asarray(np.random.RandomState(3).randn(4, 16, D), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    out_g = g.apply(variables, x)
    out_e = e.apply(variables, x)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_e),
                               rtol=1e-5, atol=1e-6)


def test_moe_expert_parallel_matches_replicated(devices):
    """Expert-sharded forward == unsharded forward (GSPMD all_to_all path)."""
    block, variables, x = _moe_setup()
    ref = block.apply(variables, x)

    mesh = mesh_lib.build_mesh({"expert": 4, "data": 2})
    shardings = sharding_lib.make_shardings(variables["params"], mesh,
                                            moe_lib.EP_RULES)
    params_sharded = jax.tree.map(jax.device_put, variables["params"], shardings)
    # expert weights actually sharded on the expert axis
    w_up = params_sharded["experts"]["w_up"]
    assert "expert" in str(w_up.sharding.spec)

    with mesh_lib.use_mesh(mesh):
        out = jax.jit(lambda p, x: block.apply({"params": p}, x))(params_sharded, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
    """With tiny capacity some tokens are dropped (output zeros for them)."""
    block = moe_lib.MoEBlock(num_experts=2, ffn_dim=16, top_k=1,
                             capacity_factor=0.25)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, D), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    out = block.apply(variables, x)
    # dropped tokens contribute exactly zero rows
    flat = np.asarray(out.reshape(-1, D))
    n_zero = (np.abs(flat).max(axis=1) == 0.0).sum()
    assert n_zero > 0


def test_moe_llama_end_to_end_ep(devices):
    """MoE-Llama trains under an expert-parallel mesh via the standard step."""
    from pytorch_distributed_training_example_tpu.core import optim, train_loop
    from pytorch_distributed_training_example_tpu.data import prefetch
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.utils.config import Config

    mesh = mesh_lib.build_mesh({"data": 2, "expert": 4})
    bundle = registry.create_model("llama_moe_tiny", seq_len=32,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(Config(lr=1e-2, optimizer="adamw"),
                                  steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("fsdp_tp", bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    # expert weights sharded over the expert axis
    w = state.params["block_0"]["moe"]["experts"]["w_up"]
    assert "expert" in str(w.sharding.spec)
    step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")),
                   donate_argnums=0)
    r = np.random.RandomState(0)
    toks = r.randint(0, 512, (8, 33)).astype(np.int32)
    with mesh_lib.use_mesh(mesh):
        b = prefetch.shard_batch({"tokens": toks[:, :-1], "targets": toks[:, 1:]},
                                 mesh_lib.batch_sharding(mesh))
        state, m = step(state, b)
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow  # ~40-105s compile on the 1-core CI host (r4 suite-budget pass)
def test_pipelined_llama_matches_sequential(devices):
    """Strategy 'pp': full Llama forward/backward through the GPipe schedule
    equals the plain scan-layers model."""
    from pytorch_distributed_training_example_tpu.core import optim, train_loop
    from pytorch_distributed_training_example_tpu.data import prefetch
    from pytorch_distributed_training_example_tpu.models import llama as llama_lib
    from pytorch_distributed_training_example_tpu.parallel import pp_lm
    from pytorch_distributed_training_example_tpu.utils.config import Config

    module = llama_lib.llama_tiny(scan_layers=True, num_layers=4)
    cfg = Config(lr=1e-2, warmup_epochs=0.0, optimizer="sgd", weight_decay=0.0)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=10)
    r = np.random.RandomState(0)
    toks = r.randint(0, 512, (16, 33)).astype(np.int32)
    batch_np = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    task = train_loop.get_task("lm")
    step = jax.jit(train_loop.make_train_step(task), donate_argnums=0)

    def run(mesh, model, rules):
        state = train_loop.create_train_state(
            model, tx, (jnp.zeros((2, 32), jnp.int32),), mesh, rules, seed=0)
        with mesh_lib.use_mesh(mesh):
            b = prefetch.shard_batch(batch_np, mesh_lib.batch_sharding(mesh))
            state, m = step(state, b)
            b = prefetch.shard_batch(batch_np, mesh_lib.batch_sharding(mesh))
            state, m2 = step(state, b)
        return float(m["loss"]), float(m2["loss"])

    ref_mesh = mesh_lib.single_device_mesh()
    ref = run(ref_mesh, module, ())

    pp_mesh = mesh_lib.build_mesh({"stage": 4, "data": 2})
    wrapper = pp_lm.PipelinedLlama(module, pp_mesh, num_microbatches=4)
    got = run(pp_mesh, wrapper, pp_lm.PP_RULES)

    # stacked block params shard over 'stage'
    assert np.isclose(ref[0], got[0], rtol=1e-4), (ref, got)
    assert np.isclose(ref[1], got[1], rtol=1e-3), (ref, got)
