"""Parity tests for the fused BN-apply/ReLU -> matmul -> BN-stats kernel
(ops/fused_bn_matmul.py), interpret mode (CPU CI)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.ops import fused_bn_matmul as fbm


def _ref(x, w, scale, bias, relu):
    xf = x.astype(jnp.float32)
    if scale is not None:
        xf = xf * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    if relu:
        xf = jnp.maximum(xf, 0.0)
    y = xf.astype(x.dtype).astype(jnp.float32) @ w.astype(jnp.float32)
    return y, jnp.mean(y, 0), jnp.var(y, 0)


@pytest.mark.parametrize("affine,relu", [(False, False), (True, True)])
def test_fused_matches_unfused(affine, relu):
    r = np.random.RandomState(0)
    N, K, C = 256, 128, 64
    x = jnp.asarray(r.randn(N, K), jnp.float32)
    w = jnp.asarray(r.randn(K, C) / np.sqrt(K), jnp.float32)
    scale = jnp.asarray(1.0 + 0.1 * r.randn(1, K), jnp.float32) if affine else None
    bias = jnp.asarray(0.1 * r.randn(1, K), jnp.float32) if affine else None

    y, mean, var = fbm.bn_stats_matmul(x, w, scale, bias, relu=relu,
                                       block_n=64, interpret=True)
    ry, rmean, rvar = _ref(x, w, scale, bias, relu)
    np.testing.assert_allclose(y, ry, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(mean, rmean, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(var, rvar, rtol=1e-4, atol=1e-5)


def test_fused_pads_odd_channels():
    """Cout=64 pads to 128 lanes; zero columns must not leak into stats."""
    r = np.random.RandomState(1)
    x = jnp.asarray(r.randn(128, 256), jnp.float32)
    w = jnp.asarray(r.randn(256, 64) / 16.0, jnp.float32)
    y, mean, var = fbm.bn_stats_matmul(x, w, relu=False, block_n=64,
                                       interpret=True)
    assert y.shape == (128, 64) and mean.shape == (64,)
    ry, rmean, rvar = _ref(x, w, None, None, False)
    np.testing.assert_allclose(mean, rmean, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(var, rvar, rtol=1e-4, atol=1e-5)


def test_fused_bf16_accumulates_fp32():
    """bf16 inputs: stats come from the fp32 matmul accumulator, not the
    rounded bf16 output."""
    r = np.random.RandomState(2)
    x = jnp.asarray(r.randn(256, 128), jnp.bfloat16)
    w = jnp.asarray(r.randn(128, 128) / 11.3, jnp.bfloat16)
    y, mean, var = fbm.bn_stats_matmul(x, w, relu=True, block_n=128,
                                       interpret=True)
    assert y.dtype == jnp.bfloat16
    xf = jnp.maximum(x.astype(jnp.float32), 0)
    ryf = xf @ w.astype(jnp.float32)
    np.testing.assert_allclose(mean, jnp.mean(ryf, 0), rtol=2e-2, atol=1e-2)
    np.testing.assert_allclose(var, jnp.var(ryf, 0), rtol=5e-2, atol=1e-2)
