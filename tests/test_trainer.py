"""Trainer-level integration (SURVEY.md §4.4): epochs, eval, checkpoint,
resume — end-to-end through the same object main.py drives."""

import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core.trainer import Trainer
from pytorch_distributed_training_example_tpu.utils.config import Config


def _cfg(tmp_path, **kw):
    base = dict(model="resnet_micro", dataset="cifar10", num_classes=10,
                image_size=32, epochs=2, global_batch_size=32, lr=0.05,
                warmup_epochs=0.0, precision="fp32", workers=0,
                steps_per_epoch=3, log_every=3,
                checkpoint_dir=str(tmp_path / "ck"))
    base.update(kw)
    return Config(**base)


@pytest.mark.slow
def test_trainer_trains_evals_checkpoints_resumes(tmp_path, devices):
    t = Trainer(_cfg(tmp_path))
    t.train()
    import os

    cks = [d for d in os.listdir(tmp_path / "ck") if d.startswith("step_")]
    assert len(cks) >= 1
    metrics_file = tmp_path / "ck" / "metrics.jsonl"
    assert metrics_file.exists() and metrics_file.read_text().strip()

    # resume continues from the stored epoch
    t2 = Trainer(_cfg(tmp_path, epochs=3, resume="auto"))
    assert t2.start_epoch == 2
    assert int(np.asarray(t2.state.step)) == 6  # 2 epochs x 3 steps


@pytest.mark.slow
def test_trainer_loss_decreases_over_epochs(tmp_path, devices):
    cfg = _cfg(tmp_path, epochs=4, steps_per_epoch=4, checkpoint_dir=None,
               lr=0.08, seed=1)
    t = Trainer(cfg)
    losses = []
    for epoch in range(cfg.epochs):
        t.train_epoch(epoch)
    # eval on the train distribution: synthetic labels are deterministic per
    # index, so the model can fit them — loss must end below chance level
    final = t.evaluate(cfg.epochs - 1)
    assert final["loss"] < 2.31  # below uniform-random CE = ln(10)
