"""Ring/Ulysses/flash attention vs the XLA oracle (SURVEY.md §4.2, §7(c))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.ops import attention as A
from pytorch_distributed_training_example_tpu.ops import flash_attention as F


def _qkv(B=2, S=64, H=4, Hkv=None, D=16, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda h: jnp.asarray(r.randn(B, S, h, D), jnp.float32)
    return mk(H), mk(Hkv or H), mk(Hkv or H)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_oracle(devices, causal):
    mesh = mesh_lib.build_mesh({"context": 8})
    q, k, v = _qkv()
    ref = A.dot_product_attention(q, k, v, causal=causal)
    out = A.ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


def test_ring_gqa_and_grads(devices):
    mesh = mesh_lib.build_mesh({"context": 4, "data": 2})
    q, k, v = _qkv(H=4, Hkv=2)
    ref = A.dot_product_attention(q, k, v, causal=True)
    out = A.ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda *a: A.ring_attention(*a, mesh=mesh, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# Documented tolerances for the ring family vs the XLA oracle: the online
# softmax reorders the reduction, so fwd agrees to rtol/atol 1e-5 in fp32
# and grads (one extra rounding through the recomputed blocks) to
# rtol 1e-4 / atol 1e-5 — same bars as the flash kernels.
RING_FWD_TOL = dict(rtol=1e-5, atol=1e-5)
RING_GRAD_TOL = dict(rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("ring", [2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_torn_last_block(devices, ring, causal):
    """S=50 does not divide ring degrees 2/4: the torn last block is padded
    and key-masked; fwd + grads stay at the documented tolerances."""
    mesh = mesh_lib.build_mesh({"context": ring, "data": 8 // ring})
    q, k, v = _qkv(B=8, S=50)
    ref = A.dot_product_attention(q, k, v, causal=causal)
    out = A.ring_attention(q, k, v, mesh=mesh, causal=causal)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               **RING_FWD_TOL)
    g_ref = jax.grad(
        lambda *a: A.dot_product_attention(*a, causal=causal).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: A.ring_attention(*a, mesh=mesh, causal=causal).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   **RING_GRAD_TOL)


@pytest.mark.parametrize("ring", [2, 4])
def test_ring_torn_gqa_grads(devices, ring):
    """Torn last block + GQA 4:1 together, fwd and grads."""
    mesh = mesh_lib.build_mesh({"context": ring, "data": 8 // ring})
    q, k, v = _qkv(B=8, S=42, H=4, Hkv=1)
    ref = A.dot_product_attention(q, k, v, causal=True)
    out = A.ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               **RING_FWD_TOL)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: A.ring_attention(*a, mesh=mesh, causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   **RING_GRAD_TOL)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_allgather_matches_oracle_and_flash(devices, causal):
    """The all-gather-KV fallback vs both oracles: the XLA reference and
    the contiguous ppermute ring (same mesh, same inputs)."""
    mesh = mesh_lib.build_mesh({"context": 4, "data": 2})
    q, k, v = _qkv()
    ref = A.dot_product_attention(q, k, v, causal=causal)
    out = A.ring_attention(q, k, v, mesh=mesh, causal=causal,
                           ring_impl="allgather")
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               **RING_FWD_TOL)
    ring = A.ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(out),
                               **RING_FWD_TOL)


def test_ring_allgather_torn_gqa_grads(devices):
    """allgather fallback with a torn last block + GQA, fwd + grads."""
    mesh = mesh_lib.build_mesh({"context": 4, "data": 2})
    q, k, v = _qkv(S=50, H=4, Hkv=1)
    ref = A.dot_product_attention(q, k, v, causal=True)
    out = A.ring_attention(q, k, v, mesh=mesh, causal=True,
                           ring_impl="allgather")
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               **RING_FWD_TOL)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: A.ring_attention(*a, mesh=mesh, causal=True,
                                    ring_impl="allgather").sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   **RING_GRAD_TOL)


def test_ring_allgather_dispatch(devices):
    """attn_impl='ring_allgather' reaches the fallback through the
    dispatcher and collapses to XLA at ctx=1."""
    mesh = mesh_lib.build_mesh({"context": 2, "data": 4})
    q, k, v = _qkv(B=8, S=32)
    ref = A.dot_product_attention(q, k, v, causal=True)
    with mesh_lib.use_mesh(mesh):
        out = A.attention(q, k, v, causal=True, impl="ring_allgather")
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               **RING_FWD_TOL)
    m1 = mesh_lib.build_mesh({"data": 8})
    with mesh_lib.use_mesh(m1):
        out1 = A.attention(q, k, v, causal=True, impl="ring_allgather")
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out1),
                               **RING_FWD_TOL)


def test_ring_bad_impl_rejected(devices):
    mesh = mesh_lib.build_mesh({"context": 2, "data": 4})
    q, k, v = _qkv(S=32)
    with pytest.raises(ValueError, match="ring_impl"):
        A.ring_attention(q, k, v, mesh=mesh, ring_impl="bogus")


@pytest.mark.parametrize("ctx", [2, 4, 8])
def test_zigzag_ring_matches_oracle(devices, ctx):
    mesh = mesh_lib.build_mesh({"context": ctx, "data": 8 // ctx})
    q, k, v = _qkv(B=8)
    ref = A.dot_product_attention(q, k, v, causal=True)
    out = A.zigzag_ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # ~40-105s compile on the 1-core CI host (r4 suite-budget pass)
def test_zigzag_ring_gqa_tp_and_grads(devices):
    mesh = mesh_lib.build_mesh({"context": 4, "model": 2})
    q, k, v = _qkv(H=4, Hkv=2)
    ref = A.dot_product_attention(q, k, v, causal=True)
    out = A.zigzag_ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: A.zigzag_ring_attention(*a, mesh=mesh, causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_zigzag_falls_back_when_indivisible(devices):
    """Sequence not divisible into 2c chunks -> contiguous ring, same result."""
    mesh = mesh_lib.build_mesh({"context": 8})
    q, k, v = _qkv(S=24)  # 24 % 16 != 0
    ref = A.dot_product_attention(q, k, v, causal=True)
    out = A.zigzag_ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_oracle(devices, causal):
    mesh = mesh_lib.build_mesh({"context": 4, "data": 2})
    q, k, v = _qkv(H=8)
    ref = A.dot_product_attention(q, k, v, causal=causal)
    out = A.ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_head_padding(devices):
    """Heads not divisible by the context shards are zero-padded (r3
    hard-errored here): values AND grads must match the oracle exactly —
    the slice vjp drops the padded heads' contributions."""
    mesh = mesh_lib.build_mesh({"context": 8})
    q, k, v = _qkv(H=4)  # 4 % 8 != 0 -> padded to 8
    ref = A.dot_product_attention(q, k, v, causal=True)
    out = A.ulysses_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(
        lambda *a: A.ulysses_attention(*a, mesh=mesh, causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ulysses_head_padding_with_tp_pads_once(devices, caplog):
    """H indivisible by BOTH tp and context: the pad target must be a
    multiple of tp*c so the recursive call doesn't pad a second time
    (r4 review finding: conditioning the pad group on the pre-pad h_ax
    double-padded 6 heads to 16). One pad == one warning."""
    import logging

    mesh = mesh_lib.build_mesh({"model": 2, "context": 4})
    q, k, v = _qkv(H=3)
    ref = A.dot_product_attention(q, k, v, causal=True)
    with caplog.at_level(logging.WARNING,
                         logger="pytorch_distributed_training_example_tpu.ops.attention"):
        out = A.ulysses_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)
    pads = [r for r in caplog.records if "zero-padding" in r.message]
    assert len(pads) == 1, [r.message for r in pads]


def test_ulysses_head_padding_gqa(devices):
    """GQA with indivisible Q heads: KV expands to full heads before the
    pad so q-to-kv head grouping stays aligned."""
    mesh = mesh_lib.build_mesh({"context": 4, "data": 2})
    q, k, v = _qkv(H=6, Hkv=2)  # 6 % 4 != 0 -> padded to 8
    ref = A.dot_product_attention(q, k, v, causal=False)
    out = A.ulysses_attention(q, k, v, mesh=mesh, causal=False)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["oneshot", "online"])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_interpret(causal, impl):
    q, k, v = _qkv(S=128)
    ref = A.dot_product_attention(q, k, v, causal=causal)
    with pltpu.force_tpu_interpret_mode():
        out = F.flash_attention(q, k, v, causal, 32, 32, impl)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["oneshot", "online"])
def test_flash_grads_interpret(impl):
    q, k, v = _qkv(S=64)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    with pltpu.force_tpu_interpret_mode():
        g_out = jax.grad(
            lambda *a: F.flash_attention(*a, True, 32, 32, impl).sum(),
            argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_fit_block_falls_to_largest_divisor():
    """Blocks must tile S exactly — flooring the grid drops rows (r3 advisor
    high: S=2560 under the 1024 defaults silently lost the last 512 query
    rows' gradients in _flash_bwd)."""
    assert F._fit_block(2560, 1024) == 640
    assert F._fit_block(3584, 1024) == 896
    assert F._fit_block(1536, 1024) == 768
    assert F._fit_block(1024, 1024) == 1024
    assert F._fit_block(512, 1024) == 512
    assert F._fit_block(96, 64) == 48


def test_flash_indivisible_block_grads_interpret():
    """S not divisible by the requested block: the online kernels must fall
    to a fitting block and produce exact grads (every row written)."""
    q, k, v = _qkv(B=1, S=96, H=2)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    with pltpu.force_tpu_interpret_mode():
        g_out = jax.grad(
            lambda *a: F.flash_attention(*a, True, 64, 64, "online").sum(),
            argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("S,D", [(2560, 128), (3584, 128), (1536, 64)])
def test_flash_eligible_shapes_trace(S, D):
    """Every shape _flash_eligible admits (S % 512 == 0) must trace through
    auto dispatch fwd+bwd with the default 1024 blocks — the r3 advisor found
    S=3584/D=128 crashing at trace time and S=2560/D=128 tracing into a
    row-dropping bwd grid. eval_shape runs the wrapper Python (plan choice,
    block fitting, grid math, asserts) without compiling."""
    q = jax.ShapeDtypeStruct((1, S, 4, D), jnp.bfloat16)
    jax.eval_shape(
        jax.grad(lambda a, b, c: F.flash_attention(a, b, c, True).sum()
                 .astype(jnp.float32)),
        q, q, q)


def test_oneshot_chunked_bwd_grads_interpret():
    """The chunked causal-skip backward (engages at Skv >= 1024 when
    CHUNK_BWD) must match the oracle exactly — invisible chunks skipped,
    visible diagonal chunks masked per-chunk (r4 kernel)."""
    assert F.CHUNK_BWD and not F.CHUNK_FWD  # measured defaults, r4
    assert F._oneshot_num_chunks(True, None, 1024, 256) == 2
    q, k, v = _qkv(B=1, S=1024, H=2, D=16)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    with pltpu.force_tpu_interpret_mode():
        g_out = jax.grad(
            lambda *a: F.flash_attention(*a, True, 1024, 1024, "oneshot").sum(),
            argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_oneshot_chunked_fwd_parity_interpret(monkeypatch):
    """The chunked forward ships gated OFF (measured ~5 ms slower e2e,
    PROFILE_GPT2.md r4) but must stay correct — including the lse output
    all LSE_LANES wide — so flipping CHUNK_FWD is safe to re-measure."""
    monkeypatch.setattr(F, "CHUNK_FWD", True)
    q, k, v = _qkv(B=1, S=1024, H=2, D=16)
    ref = A.dot_product_attention(q, k, v, causal=True)
    with pltpu.force_tpu_interpret_mode():
        out, lse = F._fwd_dispatch(q, k, v, True, 1024, 1024, "oneshot", None)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)
    # every lse lane must carry the same (real) value
    lse = np.asarray(lse)
    np.testing.assert_allclose(lse, lse[..., :1].repeat(lse.shape[-1], -1),
                               rtol=0, atol=0)
    assert np.isfinite(lse).all()


def test_gqa_repeat():
    q, k, v = _qkv(H=8, Hkv=2)
    ref = A.dot_product_attention(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2))
    out = A.dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-6)


def test_ring_and_ulysses_with_tp_heads(devices):
    """CP composes with TP: heads stay sharded on 'model' inside the ring."""
    mesh = mesh_lib.build_mesh({"context": 2, "model": 2, "data": 2})
    q, k, v = _qkv(S=32)
    ref = A.dot_product_attention(q, k, v, causal=True)
    ring = A.ring_attention(q, k, v, mesh=mesh, causal=True)
    ul = A.ulysses_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ring),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ul),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["oneshot", "online"])
def test_flash_gqa_grads_interpret(impl):
    from jax.experimental.pallas import tpu as pltpu

    q, k, v = _qkv(S=64, H=4, Hkv=2)
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    with pltpu.force_tpu_interpret_mode():
        g_out = jax.grad(
            lambda *a: F.flash_attention(*a, True, 32, 32, impl).sum(),
            argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_padded_flash_matches_oracle(causal):
    """Non-tile-aligned S (ViT's 197-token shape, scaled down) through the
    pad + kv_len-mask path must match the oracle exactly — padded keys are
    masked out of the softmax, padded query rows are sliced away."""
    q, k, v = _qkv(S=50)  # 50 % 64 != 0 -> pads to 64
    ref = A.dot_product_attention(q, k, v, causal=causal)
    with pltpu.force_tpu_interpret_mode():
        out = A.padded_flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_padded_flash_grads(causal):
    q, k, v = _qkv(S=50, H=4, Hkv=2)  # GQA + padding together
    g_ref = jax.grad(
        lambda *a: A.dot_product_attention(*a, causal=causal).sum(),
        argnums=(0, 1, 2))(q, k, v)
    with pltpu.force_tpu_interpret_mode():
        g_out = jax.grad(
            lambda *a: A.padded_flash_attention(*a, causal=causal).sum(),
            argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_lowp_probs_residual_softmax():
    """lowp_residual: forward is BIT-identical to the exact path (same f32
    softmax, same cast); backward recomputes the softmax VJP from the bf16
    probs — grads must match the exact path to bf16 rounding, and the
    custom-vjp path must not save an f32 probs residual (its only residual
    is the bf16 tensor)."""
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (2, 64, 4, 16),
                                 jnp.bfloat16) for i in range(3))
    exact = A.dot_product_attention(q, k, v, causal=True)
    lowp = A.dot_product_attention(q, k, v, causal=True, lowp_residual=True)
    np.testing.assert_array_equal(np.asarray(exact, np.float32),
                                  np.asarray(lowp, np.float32))
    ge = jax.grad(lambda *a: A.dot_product_attention(
        *a, causal=True).astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
    gl = jax.grad(lambda *a: A.dot_product_attention(
        *a, causal=True, lowp_residual=True).astype(jnp.float32).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(ge, gl):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0.05, atol=0.02)
    # the saved residual really is low-precision: no f32 tensor of the
    # probs shape [B,H,S,S] survives to the backward closure
    _, vjp = jax.vjp(lambda *a: A.dot_product_attention(
        *a, causal=True, lowp_residual=True), q, k, v)
    f32_probs_residuals = [
        x for x in jax.tree.leaves(vjp)
        if hasattr(x, "shape") and x.shape == (2, 4, 64, 64)
        and x.dtype == jnp.float32]
    assert not f32_probs_residuals


def test_oneshot_plan_dispatch_thresholds():
    """Lock in the measured auto-dispatch map (BENCH_FLASH_MICRO.json r4):
    causal forwards stream (online), backwards go one-shot whenever the
    plan fits VMEM; long-context backwards leave the dense plan (the
    fallback is streaming at D=128, online elsewhere — see
    test_auto_dispatch_is_per_direction)."""
    # GPT-2 / Llama-class shapes: the one-shot backward plan exists
    assert F._oneshot_plan(12, 1024, 1024, 64, bwd=True) is not None
    assert F._oneshot_plan(16, 2048, 2048, 128, bwd=True) is not None
    # r5 budget policy (ADVICE r4): the 16.8 MB GPT-2 backward plan is
    # admitted via the measured allowlist, not a >VMEM global cap...
    assert F._oneshot_plan(12, 1024, 1024, 64, bwd=True) == (2, 512)
    # ...so an unmeasured same-band plan (S=2048/D=64 (1,512) = 16.7 MB)
    # is no longer auto-admitted; the under-budget (1,256) is picked.
    assert F._oneshot_plan(16, 2048, 2048, 64, bwd=True) == (1, 256)
    # S=4096: fwd plan exists at the r4 budget but bwd does not ->
    # backward streams online (the measured faster choice)
    assert F._oneshot_plan(16, 4096, 4096, 128) is not None
    assert F._oneshot_plan(16, 4096, 4096, 128, bwd=True) is None
    assert F._oneshot_plan(16, 4096, 4096, 64, bwd=True) is None
    # ...but impl="oneshot" (forced) still finds a feasible fwd tiling
    assert F._oneshot_plan(16, 4096, 4096, 128, forced=True) is not None
    # tiny sequences are exempt from the fatness threshold (tests use them)
    assert F._oneshot_plan(4, 64, 64, 16) is not None
    # beyond any VMEM-feasible dense tile: no plan even forced
    assert F._oneshot_plan(16, 32768, 32768, 128, forced=True) is None


def test_auto_dispatch_is_per_direction(monkeypatch):
    """The measured r4 dispatch map must hold structurally: causal auto
    forwards stream (online), non-causal auto forwards take one-shot when
    a plan exists, and auto backwards take one-shot whenever the bwd plan
    fits. Long-context backwards fall back to the streaming one-pass
    backward at D=128 (r6) and to the online kernel pair elsewhere.
    Kernels are stubbed so this asserts the routing, not the math
    (covered elsewhere)."""
    calls = []
    monkeypatch.setattr(F, "_flash_fwd",
                        lambda *a, **k: (calls.append("online_fwd"), ("o", "l"))[1])
    monkeypatch.setattr(F, "_oneshot_fwd",
                        lambda *a, **k: (calls.append("oneshot_fwd"), ("o", "l"))[1])
    monkeypatch.setattr(F, "_flash_bwd",
                        lambda *a, **k: (calls.append("online_bwd"), ("q", "k", "v"))[1])
    monkeypatch.setattr(F, "_oneshot_bwd",
                        lambda *a, **k: (calls.append("oneshot_bwd"), ("q", "k", "v"))[1])
    monkeypatch.setattr(F, "_stream_bwd",
                        lambda *a, **k: (calls.append("stream_bwd"), ("q", "k", "v"))[1])
    q = jnp.zeros((1, 1024, 12, 64), jnp.bfloat16)
    F._fwd_dispatch(q, q, q, True, 1024, 1024, "auto", None)
    F._fwd_dispatch(q, q, q, False, 1024, 1024, "auto", None)
    res = (q, q, q, "o", "l")
    F._vjp_bwd(True, 1024, 1024, "auto", None, res, jnp.zeros_like(q))
    q4 = jnp.zeros((1, 4096, 16, 64), jnp.bfloat16)  # bwd plan infeasible, D=64
    F._vjp_bwd(True, 1024, 1024, "auto", None, (q4, q4, q4, "o", "l"),
               jnp.zeros_like(q4))
    q8 = jnp.zeros((1, 8192, 16, 128), jnp.bfloat16)  # D=128 long context
    F._vjp_bwd(True, 1024, 1024, "auto", None, (q8, q8, q8, "o", "l"),
               jnp.zeros_like(q8))
    # forced online must never take the streaming path
    F._vjp_bwd(True, 1024, 1024, "online", None, (q8, q8, q8, "o", "l"),
               jnp.zeros_like(q8))
    assert calls == ["online_fwd", "oneshot_fwd", "oneshot_bwd",
                     "online_bwd", "stream_bwd", "online_bwd"], calls


def test_stream_bwd_plan_thresholds():
    """Lock the streaming-backward admission map (r6): engages only where
    the dense one-shot bwd plan is infeasible AND D=128 (the dedicated
    long-context round; PDTX_STREAM_BWD="all" widens, "0" kills)."""
    # the S=8192 contract shape: full-Sq residency fits at (G=1, bsub=256)
    assert F._stream_bwd_plan(16, 8192, 8192, 128) == (1, 256, 512)
    # S=4096/D=128 (bwd one-shot infeasible there too): fatter subtiles fit
    assert F._stream_bwd_plan(16, 4096, 4096, 128) == (1, 512, 512)
    # D=64 keeps the measured online fallback unless widened explicitly
    assert F._stream_bwd_plan(16, 8192, 8192, 64) is None
    assert F._stream_bwd_plan(16, 8192, 8192, 64, mode="all") == (1, 512, 512)
    # kill switch
    assert F._stream_bwd_plan(16, 8192, 8192, 128, mode="0") is None
    # sub-chunk sequences have nothing to stream
    assert F._stream_bwd_plan(16, 512, 512, 128) is None


@pytest.mark.parametrize("causal", [False, True])
def test_stream_bwd_parity_d128_interpret(causal):
    """D=128 streaming one-pass backward vs the oracle VJP at S=2048
    (direct call: at this S auto dispatch still picks the dense one-shot
    bwd, but the kernel must be exact wherever its plan admits).
    Tolerances match the D=64 chunked-bwd assertions."""
    q, k, v = _qkv(B=1, S=2048, H=2, D=128)
    plan = F._stream_bwd_plan(2, 2048, 2048, 128)
    assert plan is not None
    g = jnp.asarray(np.random.RandomState(1).randn(*q.shape), jnp.float32)
    ref, vjp = jax.vjp(
        lambda *a: A.dot_product_attention(*a, causal=causal), q, k, v)
    g_ref = vjp(g)
    with pltpu.force_tpu_interpret_mode():
        out, lse = F._flash_fwd(q, k, v, causal=causal,
                                block_q=512, block_kv=512)
        g_out = F._stream_bwd(q, k, v, out, lse, g, causal=causal, plan=plan)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # interpret-mode S=8192: minutes on the CPU CI host
@pytest.mark.parametrize("causal", [False, True])
def test_stream_bwd_parity_s8192_interpret(causal):
    """The exact contract shape's (S=8192, D=128) plan, end to end."""
    q, k, v = _qkv(B=1, S=8192, H=1, D=128)
    plan = F._stream_bwd_plan(1, 8192, 8192, 128)
    assert plan == (1, 256, 512)
    g = jnp.asarray(np.random.RandomState(1).randn(*q.shape), jnp.float32)
    ref, vjp = jax.vjp(
        lambda *a: A.dot_product_attention(*a, causal=causal), q, k, v)
    g_ref = vjp(g)
    with pltpu.force_tpu_interpret_mode():
        out, lse = F._flash_fwd(q, k, v, causal=causal,
                                block_q=1024, block_kv=1024)
        g_out = F._stream_bwd(q, k, v, out, lse, g, causal=causal, plan=plan)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_stream_bwd_auto_path_gqa_grads_interpret(monkeypatch):
    """End to end through flash_attention's custom VJP: when the one-shot
    bwd plan is infeasible and the streaming plan admits, auto grads route
    through the streaming backward — including the GQA head fold."""
    monkeypatch.setattr(F, "_oneshot_plan", lambda *a, **k: None)
    monkeypatch.setattr(F, "STREAM_BWD", "all")  # small-D test shape
    q, k, v = _qkv(B=1, S=1024, H=4, Hkv=2, D=16)
    assert F._stream_bwd_plan(4, 1024, 1024, 16) is not None
    g_ref = jax.grad(lambda *a: A.dot_product_attention(*a, causal=True).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    with pltpu.force_tpu_interpret_mode():
        g_out = jax.grad(
            lambda *a: F.flash_attention(*a, True, 512, 512, "auto").sum(),
            argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_padded_flash_eligibility_gates():
    """auto uses the padded path only at >=1024 padded tokens (ViT's 197
    measured slower through it); explicit use allows any plannable shape."""
    q = jnp.zeros((2, 197, 12, 64), jnp.bfloat16)
    if jax.default_backend() == "cpu":
        assert not A._padded_flash_eligible(q, q, explicit=False)
        assert not A._padded_flash_eligible(q, q)  # CPU: never
    # pure-shape logic (backend-independent pieces)
    assert A._round_up(197, A.PAD_MULTIPLE) == 256
    assert A._round_up(1024, A.PAD_MULTIPLE) == 1024
