#!/usr/bin/env python
"""Isolated flash-attention timing at LM shapes (fwd and fwd+bwd).

Prints per-config: measured ms, attention-FLOPs, achieved TF/s and
fraction-of-peak, flash kernel vs XLA dot-product attention. Informs the
GPT-2 MFU ceiling analysis (LM_SWEEP.json).

Timing is SLOPE-BASED: chained iterations inside one ``lax.scan`` under
jit, synced by a host transfer (``block_until_ready`` alone does not
synchronize through the axon tunnel), measured at two trip counts; the
per-iteration time is the slope, which cancels the ~75 ms fixed dispatch
cost the tunnel adds per executable call.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np


def attn_flops(B, H, S, D, causal=True, bwd=False):
    """MAC-counted FLOPs for qk+pv; bwd adds recompute + dq/dk/dv dots."""
    f = 2 * 2 * B * H * S * S * D  # qk and pv, 2 FLOPs per MAC
    if causal:
        f /= 2
    return f * (3.5 if bwd else 1.0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--peak-tflops", type=float, default=197.0)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--out", default=None)
    p.add_argument("--shapes", default=None,
                   help="comma-separated BxHxSxD entries to run (default: "
                        "all three LM shapes); lets long runs split across "
                        "invocations — with --merge, rows append into --out")
    p.add_argument("--merge", action="store_true",
                   help="append rows into an existing --out file")
    p.add_argument("--block-sweep", action="store_true",
                   help="sweep block_q x block_kv for the online kernel "
                        "instead of comparing impls — the D=128 long-S "
                        "tile-size search (PROFILE_LLAMA.md lever 1); rows "
                        "carry block_q/block_kv and merge by that key")
    p.add_argument("--blocks", default="256,512,1024",
                   help="comma-separated candidate block sizes for "
                        "--block-sweep (applied to both axes)")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.ops import (
        flash_attention as fa)
    from pytorch_distributed_training_example_tpu.ops import (
        attention as attn_lib)

    def xla_attn(q, k, v, causal=True):
        return attn_lib.dot_product_attention(q, k, v, causal=causal)

    def timed(fn_one, q, k, v):
        """ms per iteration of q <- fn_one(q, k, v): two-length slope."""
        def at_length(L):
            def body(qq, _):
                return fn_one(qq, k, v), ()

            @jax.jit
            def run(q):
                out, _ = jax.lax.scan(body, q, None, length=L)
                return jnp.float32(out[0, 0, 0, 0])

            np.asarray(run(q))  # compile + warm
            dt = float("inf")
            for _ in range(4):
                t0 = time.perf_counter()
                np.asarray(run(q))
                dt = min(dt, time.perf_counter() - t0)
            return dt

        L1, L2 = args.iters, 4 * args.iters
        return max(at_length(L2) - at_length(L1), 1e-9) / (L2 - L1) * 1e3

    # Stock JAX TPU Pallas kernel (jax.experimental.pallas.ops.tpu) as an
    # INDEPENDENT yardstick for the in-repo kernels (VERDICT r4 missing
    # #3): if the stock kernel beats ours at a shape, the gap is closable
    # in-kernel; if it lands in the same band, the thin-contraction-wall
    # claim (PROFILE_GPT2.md) gets outside confirmation. Measured at its
    # native BHSD layout (no transpose overhead charged to it).
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as stock_fa)
    except Exception:
        stock_fa = None

    import math

    shapes = ((16, 12, 1024, 64), (4, 12, 2048, 64), (2, 16, 4096, 128))
    if args.shapes:
        shapes = tuple(tuple(int(x) for x in s.split("x"))
                       for s in args.shapes.split(","))
    rows = []
    for (B, H, S, D) in shapes:
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
        k = jax.random.normal(ks[1], (B, S, H, D), jnp.bfloat16)
        v = jax.random.normal(ks[2], (B, S, H, D), jnp.bfloat16)
        # BHSD copies for the stock kernel's native layout
        qh, kh, vh = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))

        def oneshot(q, k, v):
            return fa.flash_attention(q, k, v, True, fa.DEFAULT_BLOCK_Q,
                                      fa.DEFAULT_BLOCK_KV, "oneshot")

        def online(q, k, v):
            return fa.flash_attention(q, k, v, True, fa.DEFAULT_BLOCK_Q,
                                      fa.DEFAULT_BLOCK_KV, "online")

        def stock(q, k, v, _scale=1.0 / math.sqrt(D)):
            return stock_fa(q, k, v, causal=True, sm_scale=_scale)

        if args.block_sweep:
            # Tile-size search for the online kernel only: the oneshot path
            # picks its own plan and XLA has no block knob. Winning entries
            # graduate into fa.ONLINE_BLOCK_TABLE.
            cand = [int(x) for x in args.blocks.split(",")]
            impls = []
            for bq in cand:
                for bkv in cand:
                    if bq > S or bkv > S:
                        continue

                    def online_b(q, k, v, bq=bq, bkv=bkv):
                        return fa.flash_attention(q, k, v, True, bq, bkv,
                                                  "online")

                    impls.append(("online", online_b, (q, k, v),
                                  {"block_q": bq, "block_kv": bkv}))
        else:
            impls = [("oneshot", oneshot, (q, k, v), {}),
                     ("online", online, (q, k, v), {}),
                     ("xla", xla_attn, (q, k, v), {})]
            if stock_fa is not None:
                impls.append(("stock_jax_pallas", stock, (qh, kh, vh), {}))
        for name, fn, (qi, ki, vi), tags in impls:
            ms_f = timed(fn, qi, ki, vi)

            def grad_step(qq, k, v, fn=fn):
                # All three grads consumed: taking only dq lets XLA DCE the
                # online path's separate dk/dv kernel and understates bwd.
                dq, dk, dv = jax.grad(
                    lambda q3, k3, v3: jnp.sum(
                        fn(q3, k3, v3).astype(jnp.float32)) * 1e-3,
                    argnums=(0, 1, 2))(qq, k, v)
                return (dq + dk + dv).astype(qq.dtype)

            ms_b = timed(grad_step, qi, ki, vi)

            for tag, ms, bwd in (("fwd", ms_f, False),
                                 ("fwd+bwd", ms_b, True)):
                fl = attn_flops(B, H, S, D, bwd=bwd)
                tf = fl / (ms / 1e3) / 1e12
                rows.append({"impl": name, "pass": tag, "B": B, "H": H,
                             "S": S, "D": D, **tags, "ms": round(ms, 3),
                             "tflops": round(tf, 1),
                             "frac_peak": round(tf / args.peak_tflops, 3)})
                print(json.dumps(rows[-1]), file=sys.stderr, flush=True)

    measured = len(rows)
    if args.out:
        doc = {"peak_tflops": args.peak_tflops}
        if args.merge:
            import os
            if os.path.exists(args.out):
                with open(args.out) as f:
                    doc = json.load(f)  # preserve unknown sections verbatim
                if doc.get("peak_tflops", args.peak_tflops) != args.peak_tflops:
                    raise SystemExit(
                        f"--merge: existing {args.out} is normalized to "
                        f"peak_tflops={doc['peak_tflops']}, this run to "
                        f"{args.peak_tflops}; frac_peak values would mix")
                key = lambda r: (r["impl"], r["pass"], r["B"], r["H"],
                                 r["S"], r["D"], r.get("block_q"),
                                 r.get("block_kv"))
                fresh = {key(r) for r in rows}
                # re-measured keys REPLACE stale rows instead of duplicating
                rows = [r for r in doc.get("rows", [])
                        if key(r) not in fresh] + rows
        doc["rows"] = rows
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps({"rows_measured": measured, "rows_total": len(rows)}))


if __name__ == "__main__":
    sys.path.insert(0, ".")
    main()
