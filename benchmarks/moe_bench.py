#!/usr/bin/env python
"""MoE dispatch microbenchmark: gather vs einsum vs dropless at real
token counts (VERDICT r2 #8; dropless added r14).

Times one MoE block — router + dispatch + stacked-expert FFN + combine —
fwd+bwd at GPT-2-scale dims (d=768, ffn=3072, E=8, top-2) across token
counts, for the dispatch implementations in ``parallel/moe.py``. The
einsum path's O(T*E*C) dispatch mask is measured against the gather
path's O(E*C*d + T*k) slot table and the dropless path's ragged grouped
matmul (ops/grouped_matmul.py — no capacity buffer at all).

Slope-timed (two scan trip counts — cancels the ~75 ms fixed dispatch
cost of the tunnel; see BENCH_FLASH_MICRO.json).

A second, chipless section reports the AOT routed-region byte model per
impl at the llama_moe bench shape (b4 s2048) via profile_step.aot_report
— the same numbers check_regression.py --aot-bytes gates. "Routed-region
bytes" = the sum over the moe_* named-scope regions of one train step
(everything inside the MoE block: router + dispatch + experts + combine
+ aux), as opposed to the dense trunk (non_moe).

``--ep-sweep`` (r17) benches the dropless EP transports instead: per
EP degree, the AOT collective byte census at the moe_tiny train-step
shape (llama_moe_tiny b2 s128 — the golden.json ``... ep2 *`` rows) and
a measured MoE-block step time at moe_tiny dims under an
``{"expert": ep}`` mesh for each ``ep_dispatch`` mode. Bytes are
chipless facts; the ms column is this host's devices (fake CPU devices
off-chip — relative, not headline, numbers).

    python benchmarks/moe_bench.py [--out BENCH_MOE.json]
    python benchmarks/moe_bench.py --ep-sweep [--ep-degrees 1,2,4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

D_MODEL = 768
FFN = 3072
EXPERTS = 8


def bench_point(T, impl):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_example_tpu.parallel.moe import MoEBlock

    block = MoEBlock(EXPERTS, FFN, dispatch_impl=impl, dtype=jnp.bfloat16,
                     param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, T, D_MODEL),
                          jnp.bfloat16)
    variables = block.init({"params": jax.random.PRNGKey(1)}, x, train=False)
    params = variables["params"]

    def loss_fn(params, x):
        out, _ = block.apply({"params": params}, x, train=False,
                             mutable=["losses"])
        return jnp.sum(out.astype(jnp.float32)) * 1e-3

    grad_fn = jax.grad(loss_fn, argnums=(0, 1))

    def at_length(L):
        def body(carry, _):
            gp, gx = grad_fn(params, x + carry.astype(x.dtype))
            s = sum(jnp.sum(g.astype(jnp.float32))
                    for g in jax.tree.leaves(gp))
            return (s * 1e-30 + jnp.float32(jnp.sum(
                gx.astype(jnp.float32)) * 1e-30)).astype(jnp.float32), ()

        @jax.jit
        def run(c0):
            c, _ = jax.lax.scan(body, c0, None, length=L)
            return c

        np.asarray(run(jnp.float32(0)))
        dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(run(jnp.float32(0)))
            dt = min(dt, time.perf_counter() - t0)
        return dt

    L1, L2 = 10, 40
    sec = max(at_length(L2) - at_length(L1), 1e-9) / (L2 - L1)
    # per-token expert FLOPs: top-2 x (3 matmuls of d*ffn) x 2 MAC x fwd+2bwd
    flops = T * 2 * 3 * D_MODEL * FFN * 2 * 3
    return {"tokens": T, "dispatch": impl, "ms": round(sec * 1e3, 3),
            "tokens_per_sec": round(T / sec),
            "expert_tflops": round(flops / sec / 1e12, 1)}


def aot_bytes_rows(impls):
    """Routed-region AOT byte model per dispatch impl at the llama_moe
    bench shape — chipless, so it runs (and means the same thing) on the
    CI host and next to the chip timings."""
    from benchmarks import profile_step

    rows = []
    for impl in impls:
        r = profile_step.aot_report("llama_moe", per_chip_batch=4,
                                    seq_len=2048, moe_dispatch_impl=impl)
        regions = {tag: row["gbytes_modeled"]
                   for tag, row in r["regions"].items()}
        rows.append({
            "dispatch": impl,
            "routed_gb": round(sum(v for tag, v in regions.items()
                                   if tag.startswith("moe_")), 3),
            "regions_gb": regions,
            "xla_flops_per_step": r["xla_flops_per_step"],
        })
        print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
    return rows


# moe_tiny block dims (models/llama.py llama_moe_tiny): the EP sweep's
# measured leg times one MoE block at these dims so the rows line up with
# the chipless AOT census at the llama_moe_tiny train-step shape.
TINY = {"d_model": 128, "ffn": 256, "experts": 8, "top_k": 2}


def ep_bench_point(T, ep, ep_dispatch):
    """Slope-timed fwd+bwd of one dropless MoE block at moe_tiny dims
    under an ``{"expert": ep}`` mesh (first ep local devices)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib)
    from pytorch_distributed_training_example_tpu.parallel.moe import (
        MoEBlock)

    if len(jax.devices()) < ep:
        return {"tokens": T, "ep": ep, "ep_dispatch": ep_dispatch,
                "ok": False,
                "error": f"needs {ep} devices, have {len(jax.devices())}"}
    mesh = mesh_lib.build_mesh({"expert": ep}, devices=jax.devices()[:ep])
    block = MoEBlock(TINY["experts"], TINY["ffn"], top_k=TINY["top_k"],
                     capacity_factor=1.0, dispatch_impl="dropless",
                     ep_dispatch=ep_dispatch, dtype=jnp.bfloat16,
                     param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, T, TINY["d_model"]),
                          jnp.bfloat16)
    with mesh_lib.use_mesh(mesh):
        variables = block.init({"params": jax.random.PRNGKey(1)}, x,
                               train=False)
        params = variables["params"]

        def loss_fn(params, x):
            out, _ = block.apply({"params": params}, x, train=False,
                                 mutable=["losses"])
            return jnp.sum(out.astype(jnp.float32)) * 1e-3

        grad_fn = jax.grad(loss_fn, argnums=(0, 1))

        def at_length(L):
            def body(carry, _):
                gp, gx = grad_fn(params, x + carry.astype(x.dtype))
                s = sum(jnp.sum(g.astype(jnp.float32))
                        for g in jax.tree.leaves(gp))
                return (s * 1e-30 + jnp.float32(jnp.sum(
                    gx.astype(jnp.float32)) * 1e-30)).astype(jnp.float32), ()

            @jax.jit
            def run(c0):
                c, _ = jax.lax.scan(body, c0, None, length=L)
                return c

            np.asarray(run(jnp.float32(0)))
            dt = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(run(jnp.float32(0)))
                dt = min(dt, time.perf_counter() - t0)
            return dt

        L1, L2 = 5, 20
        sec = max(at_length(L2) - at_length(L1), 1e-9) / (L2 - L1)
    return {"tokens": T, "ep": ep, "ep_dispatch": ep_dispatch,
            "ms": round(sec * 1e3, 3), "tokens_per_sec": round(T / sec)}


def ep_sweep_rows(degrees, modes, T):
    """Per-EP-degree rows: chipless routed/a2a collective bytes from the
    AOT census (llama_moe_tiny b2 s128, the golden-gated shape) joined
    with the measured moe_tiny block step time on this host."""
    from benchmarks import profile_step

    rows = []
    for ep in degrees:
        ep_modes = ["replicated"] if ep == 1 else modes
        for mode in ep_modes:
            row = {"ep": ep, "ep_dispatch": mode}
            try:
                r = profile_step.aot_report(
                    "llama_moe_tiny", per_chip_batch=2, seq_len=128,
                    moe_dispatch_impl="dropless", moe_capacity_factor=1.0,
                    moe_ep_dispatch=mode, ep_degree=ep)
                coll = r["collectives"]
                opb = {op: v["bytes"]
                       for op, v in coll["by_opcode"].items()}
                row.update(
                    routed_mb=round(coll["moe_bytes"] / 1e6, 3),
                    a2a_mb=round(opb.get("all-to-all", 0) / 1e6, 3),
                    allgather_mb=round(opb.get("all-gather", 0) / 1e6, 3),
                    collective_total_mb=round(coll["total_bytes"] / 1e6, 3))
            except Exception as e:  # chipless leg short on devices, etc.
                row.update(ok=False, error=str(e)[:200])
            row.update(ep_bench_point(T, ep, mode))
            rows.append(row)
            print(json.dumps(row), file=sys.stderr, flush=True)
    return rows


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="BENCH_MOE.json")
    p.add_argument("--tokens", default="4096,16384,65536")
    p.add_argument("--aot-impls", default="gather,sort,dropless",
                   help="dispatch impls for the routed-region AOT byte "
                        "section (empty string skips it)")
    p.add_argument("--ep-sweep", action="store_true",
                   help="bench the dropless EP transports per EP degree "
                        "(AOT collective bytes + measured moe_tiny block "
                        "step time) instead of the dispatch sweep")
    p.add_argument("--ep-degrees", default="1,2,4",
                   help="EP degrees for --ep-sweep (must divide the "
                        "expert count and the local device count)")
    p.add_argument("--ep-modes", default="replicated,a2a,a2a_overlap",
                   help="ep_dispatch modes per degree for --ep-sweep")
    p.add_argument("--ep-tokens", type=int, default=4096,
                   help="token count for the --ep-sweep measured leg")
    args = p.parse_args(argv)
    if args.ep_sweep:
        degrees = [int(x) for x in args.ep_degrees.split(",") if x]
        if "jax" not in sys.modules and max(degrees) > 1:
            # chipless hosts: the EP meshes need that many devices, and the
            # flag only takes effect before jax initializes
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count={max(degrees)}")
        import jax

        rows = ep_sweep_rows(degrees,
                             [s for s in args.ep_modes.split(",") if s],
                             args.ep_tokens)
        out = {
            "bench": "moe_dropless_ep_dispatch_sweep",
            "device": jax.devices()[0].device_kind,
            "dims": {**TINY, "capacity_factor": 1.0},
            "aot_shape": {"model": "llama_moe_tiny", "per_chip_batch": 2,
                          "seq_len": 128},
            "pass": "fwd+bwd (params and input grads)",
            "timing": "two-trip-count slope, chained scan, best of 3",
            "rows": rows,
        }
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps({"rows": rows, "out": args.out}))
        return 0
    import jax

    rows = []
    for T in [int(x) for x in args.tokens.split(",")]:
        for impl in ("gather", "einsum", "dropless"):
            try:
                rows.append(bench_point(T, impl))
            except Exception as e:
                msg = str(e)
                rows.append({"tokens": T, "dispatch": impl, "ok": False,
                             "error": ("OOM" if "RESOURCE_EXHAUSTED" in msg
                                       else msg[:200])})
            print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
    aot_impls = [s for s in args.aot_impls.split(",") if s]
    out = {
        "bench": "moe_dispatch_gather_vs_einsum_vs_dropless",
        "device": jax.devices()[0].device_kind,
        "dims": {"d_model": D_MODEL, "ffn": FFN, "experts": EXPERTS,
                 "top_k": 2, "capacity_factor": 1.25},
        "pass": "fwd+bwd (params and input grads)",
        "timing": "two-trip-count slope, chained scan, best of 3 per point",
        "rows": rows,
        "aot_routed_bytes": {
            "model": "llama_moe", "per_chip_batch": 4, "seq_len": 2048,
            "note": "chipless profile_step.aot_report; routed_gb = sum "
                    "of moe_* region modeled bytes",
            "rows": aot_bytes_rows(aot_impls),
        } if aot_impls else None,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"rows": rows, "out": args.out}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
