#!/usr/bin/env python
"""Golden-metric regression gate (SURVEY.md §4.5).

Compare a bench.py JSON result (stdin or file) against benchmarks/golden.json
for the device it ran on; exit 1 if any matched metric regressed more than
``--tolerance`` (default 10%). Metrics or devices without a golden entry are
reported but never fail — new hardware/new benchmarks need a first recording.

Usage:
    python bench.py | python benchmarks/check_regression.py
    python benchmarks/check_regression.py BENCH_r02.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "golden.json")


def load_golden(path: str = GOLDEN_PATH) -> dict:
    with open(path) as fh:
        return {k: v for k, v in json.load(fh).items()
                if not k.startswith("_")}


def iter_rows(result: dict):
    """A bench result line carries the headline row plus optional extras.lm."""
    yield result["metric"], float(result["value"]), result.get("extra", {})
    lm = result.get("extra", {}).get("lm")
    if lm:
        yield lm["metric"], float(lm["value"]), result.get("extra", {})


def check(result: dict, golden: dict, tolerance: float = 0.10):
    """Returns (failures, report_lines); a failure is a >tolerance drop."""
    device = result.get("extra", {}).get("device", "")
    table = golden.get(device, {})
    failures, report = [], []
    for metric, value, _ in iter_rows(result):
        ref = table.get(metric)
        if not ref:
            report.append(f"NO-GOLDEN {metric} ({device}): measured {value}")
            continue
        ratio = value / ref["value"]
        line = (f"{metric} ({device}): {value:.1f} vs golden "
                f"{ref['value']:.1f} ({ratio:.2%})")
        if ratio < 1.0 - tolerance:
            failures.append(line)
            report.append("REGRESSION " + line)
        else:
            report.append("OK " + line)
    return failures, report


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("result", nargs="?", help="bench JSON file (default: stdin)")
    p.add_argument("--tolerance", type=float, default=0.10)
    args = p.parse_args(argv)
    raw = open(args.result).read() if args.result else sys.stdin.read()
    # Accept a driver BENCH_r{N}.json wrapper (pretty-printed, result under
    # "parsed") or piped bench.py output (last stdout line is the JSON).
    try:
        data = json.loads(raw)
    except json.JSONDecodeError:
        data = json.loads(raw.strip().splitlines()[-1])
    result = data.get("parsed", data)
    failures, report = check(result, load_golden(), args.tolerance)
    for line in report:
        print(line)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
