#!/usr/bin/env python
"""Golden-metric regression gate (SURVEY.md §4.5).

Compare a bench.py JSON result (stdin or file) against benchmarks/golden.json
for the device it ran on; exit 1 if any matched metric regressed more than
``--tolerance`` (default 10%). Metrics or devices without a golden entry are
reported but never fail — new hardware/new benchmarks need a first recording.

``--aot-bytes`` gates a ``profile_step.py --aot`` report instead: per-region
modeled HBM bytes versus the ``aot_regions`` section of golden.json. Bytes
regress UPWARD (more traffic = worse), it needs no chip (the numbers are
facts of the lowered program), and ``--record`` writes the first golden.

Usage:
    python bench.py | python benchmarks/check_regression.py
    python benchmarks/check_regression.py BENCH_r02.json
    python benchmarks/profile_step.py --model llama_moe --aot \
        --moe-dispatch gather | python benchmarks/check_regression.py \
        --aot-bytes
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "golden.json")


def load_golden(path: str = GOLDEN_PATH) -> dict:
    with open(path) as fh:
        return {k: v for k, v in json.load(fh).items()
                if not k.startswith("_")}


def iter_rows(result: dict):
    """A bench result line carries the headline row plus optional extras.lm."""
    yield result["metric"], float(result["value"]), result.get("extra", {})
    lm = result.get("extra", {}).get("lm")
    if lm:
        yield lm["metric"], float(lm["value"]), result.get("extra", {})


def check_health(jsonl_path: str):
    """Scan a run's metrics.jsonl for non-finite training-health scalars.

    A golden run whose health pack went NaN/inf mid-run produced its
    throughput number while training garbage — flag it even if the
    images/sec headline looks fine. (``json.loads`` accepts the bare
    ``NaN``/``Infinity`` tokens Python's json.dump emits, so the scan sees
    them as real floats.)
    """
    import math

    failures, report = [], []
    with open(jsonl_path) as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if row.get("kind") not in (None, "train", "health"):
                continue
            bad = [k for k, v in row.items()
                   if not isinstance(v, bool) and isinstance(v, (int, float))
                   and not math.isfinite(v)]
            if bad:
                msg = (f"{jsonl_path}:{ln}: non-finite health scalar(s) "
                       f"{bad} at step {row.get('step', '?')}")
                failures.append(msg)
                report.append("NON-FINITE " + msg)
    if not failures:
        report.append(f"HEALTH-OK {jsonl_path}: all scalars finite")
    return failures, report


def check(result: dict, golden: dict, tolerance: float = 0.10):
    """Returns (failures, report_lines); a failure is a >tolerance drop."""
    device = result.get("extra", {}).get("device", "")
    table = golden.get(device, {})
    failures, report = [], []
    for metric, value, _ in iter_rows(result):
        ref = table.get(metric)
        if not ref:
            report.append(f"NO-GOLDEN {metric} ({device}): measured {value}")
            continue
        ratio = value / ref["value"]
        line = (f"{metric} ({device}): {value:.1f} vs golden "
                f"{ref['value']:.1f} ({ratio:.2%})")
        if ratio < 1.0 - tolerance:
            failures.append(line)
            report.append("REGRESSION " + line)
        else:
            report.append("OK " + line)
    return failures, report


def check_goodput(path: str, min_coverage: float = 0.95,
                  cluster: bool = False):
    """Gate a run's ``goodput.json`` on instrumentation coverage.

    Accepts both single-attempt files and the merged multi-attempt files an
    elastic/supervisor run writes (``attempts`` > 1, with the inter-attempt
    gap folded into the ``restart`` badput bucket). The gate is on cumulative
    ``coverage`` — spans must explain at least ``min_coverage`` of the total
    wall clock across every attempt, so a restart tax that the telemetry
    failed to attribute shows up as a failure rather than vanishing.

    With ``cluster=True`` the input is a fleet launcher's
    ``cluster_goodput.json`` (fleetobs.aggregate_cluster_goodput): an
    aggregate over independent jobs, where distinct run_ids are the expected
    shape — the mixed-run refusal below is the *single-run* staleness check
    and does not apply. The coverage floor is then cluster-wide
    (wall-weighted across jobs).
    """
    failures, report = [], []
    try:
        with open(path) as fh:
            data = json.load(fh)
        coverage = float(data["coverage"])
        wall = float(data["wall_s"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        msg = f"goodput {path}: unreadable or malformed ({e})"
        failures.append(msg)
        report.append("MALFORMED " + msg)
        return failures, report
    attempts = int(data.get("attempts", 1))
    restart_s = float(data.get("categories_s", {}).get("restart", 0.0))
    run_ids = [r for r in (data.get("run_ids") or []) if r]
    if cluster:
        jobs = data.get("jobs") or []
        if not data.get("cluster"):
            msg = (f"goodput {path}: --cluster expects a fleet "
                   "cluster_goodput.json (aggregate_cluster_goodput), got a "
                   "single-run summary")
            failures.append(msg)
            report.append("MALFORMED " + msg)
            return failures, report
        line = (f"cluster goodput {path}: coverage {coverage:.3f} over "
                f"{wall:.1f}s device-wall, {len(jobs)} job(s) "
                f"{sorted(jobs)}, {len(set(run_ids))} run id(s), "
                f"{attempts} attempt(s), restart tax {restart_s:.1f}s")
        if coverage < min_coverage:
            failures.append(line + f" — below floor {min_coverage}")
            report.append("REGRESSION " + line + f" (floor {min_coverage})")
        else:
            report.append("OK " + line)
        return failures, report
    # Mixed-run refusal: a cumulative/fleet summary stamped with more than
    # one run id silently sums UNRELATED attempts (stale artifacts in a
    # reused checkpoint dir) — its coverage and goodput are meaningless, so
    # fail loudly instead of gating on fiction.
    if len(set(run_ids)) > 1:
        msg = (f"goodput {path}: merged across {len(set(run_ids))} different "
               f"runs {sorted(set(run_ids))} — refusing to gate a mixed-run "
               f"summary (stale artifacts? clear the dir or re-merge)")
        failures.append(msg)
        report.append("MIXED-RUN " + msg)
        return failures, report
    line = (f"goodput {path}: coverage {coverage:.3f} over {wall:.1f}s wall, "
            f"{attempts} attempt(s), restart tax {restart_s:.1f}s")
    if coverage < min_coverage:
        failures.append(line + f" — below floor {min_coverage}")
        report.append("REGRESSION " + line + f" (floor {min_coverage})")
    else:
        report.append("OK " + line)
    return failures, report


def check_ttfs(path: str, max_ratio: float = 0.8):
    """Gate warm-restart time-to-first-step against cold (goodput.json).

    The executable cache (core/xcache.py) exists to make restarts fast; this
    gate keeps that property from silently rotting. ``ttfs_history`` (one
    entry per attempt, carried across supervisor restarts by the telemetry
    merge) is split by mode: every ``warm`` attempt must beat the SLOWEST
    ``cold`` attempt by at least ``max_ratio`` (warm < max_ratio * cold).

    Neutral by design when there is nothing to compare: a run whose cache
    was missing, corrupted (quarantined -> cold recompile) or never
    populated has no warm entries — that is the cache layer behaving
    correctly, not a regression, so the gate reports OK and moves on. An
    unreadable goodput.json still fails loudly, same as --goodput.
    """
    failures, report = [], []
    try:
        with open(path) as fh:
            data = json.load(fh)
        history = list(data.get("ttfs_history") or [])
    except (OSError, ValueError, AttributeError, TypeError) as e:
        msg = f"ttfs {path}: unreadable or malformed ({e})"
        failures.append(msg)
        report.append("MALFORMED " + msg)
        return failures, report
    try:
        cold = [float(h["ttfs_s"]) for h in history if h.get("mode") == "cold"]
        warm = [float(h["ttfs_s"]) for h in history if h.get("mode") == "warm"]
    except (ValueError, KeyError, TypeError) as e:
        msg = f"ttfs {path}: malformed ttfs_history entry ({e})"
        failures.append(msg)
        report.append("MALFORMED " + msg)
        return failures, report
    if not warm or not cold:
        report.append(
            f"OK ttfs {path}: no warm/cold pair to compare "
            f"({len(cold)} cold, {len(warm)} warm attempt(s)) — neutral")
        return failures, report
    worst_warm, worst_cold = max(warm), min(cold)
    line = (f"ttfs {path}: warm {worst_warm:.3f}s vs cold {worst_cold:.3f}s "
            f"(x{worst_warm / worst_cold:.2f}, floor x{max_ratio}) over "
            f"{len(cold)} cold / {len(warm)} warm attempt(s)")
    if worst_warm >= max_ratio * worst_cold:
        failures.append(line + " — executable cache is not paying for itself")
        report.append("REGRESSION " + line)
    else:
        report.append("OK " + line)
    return failures, report


def check_slo(path: str):
    """Gate a serving run's ``slo.jsonl`` (serve/slo.py SLOTracker.flush).

    Well-formedness contract: every line parses; exactly one ``slo_header``
    (first row) naming the window size; at least one ``slo_window`` row,
    each with finite quantiles, sample counts within [1, window] (the
    window-coverage check — a count of 0 means a phantom row, above the
    window means the deque invariant broke), and attainment in [0, 1];
    exactly one ``slo_summary`` with finite attainment; and a single
    run_id across all rows (stale-artifact refusal, same spirit as the
    goodput mixed-run gate).
    """
    failures, report = [], []
    rows = []
    try:
        with open(path) as fh:
            for i, line in enumerate(fh, 1):
                if line.strip():
                    rows.append(json.loads(line))
    except (OSError, ValueError) as e:
        msg = f"slo {path}: unreadable or malformed line {len(rows) + 1} ({e})"
        failures.append(msg)
        report.append("MALFORMED " + msg)
        return failures, report

    def fail(msg):
        failures.append(f"slo {path}: {msg}")
        report.append(f"MALFORMED slo {path}: {msg}")

    headers = [r for r in rows if r.get("kind") == "slo_header"]
    windows = [r for r in rows if r.get("kind") == "slo_window"]
    summaries = [r for r in rows if r.get("kind") == "slo_summary"]
    if len(headers) != 1 or rows[0] is not headers[0]:
        fail(f"expected exactly one leading slo_header, got {len(headers)}")
        return failures, report
    run_ids = sorted({str(r.get("run_id")) for r in rows})
    if len(run_ids) > 1:
        fail(f"rows span {len(run_ids)} run ids {run_ids} — stale "
             f"artifacts? clear the dir or re-flush")
        return failures, report
    window = headers[0].get("window")
    if not isinstance(window, int) or window < 1:
        fail(f"header window must be a positive int, got {window!r}")
        return failures, report
    if not windows:
        fail("no slo_window rows (no samples observed?)")
    for r in windows:
        key = f"{r.get('replica')}/{r.get('role')}"
        n_t, n_i = r.get("ttft_count", 0), r.get("itl_count", 0)
        if not (isinstance(n_t, int) and isinstance(n_i, int)) \
                or n_t + n_i < 1 or n_t > window or n_i > window:
            fail(f"window {key}: counts ttft={n_t} itl={n_i} outside "
                 f"[1, {window}] coverage")
            continue
        for metric in ("ttft_p50_ms", "ttft_p99_ms", "itl_p50_ms",
                       "itl_p99_ms", "attainment"):
            v = r.get(metric)
            if v is not None and not (isinstance(v, (int, float))
                                      and math.isfinite(v)):
                fail(f"window {key}: non-finite {metric}={v!r}")
        att = r.get("attainment")
        if isinstance(att, (int, float)) and not 0.0 <= att <= 1.0:
            fail(f"window {key}: attainment {att} outside [0, 1]")
    if len(summaries) != 1:
        fail(f"expected exactly one slo_summary, got {len(summaries)}")
    else:
        att = summaries[0].get("attainment")
        if not (isinstance(att, (int, float)) and math.isfinite(att)
                and 0.0 <= att <= 1.0):
            fail(f"summary attainment {att!r} not a finite [0, 1] value")
    if not failures:
        s = summaries[0]
        report.append(
            f"OK slo {path}: run {run_ids[0]}, {len(windows)} window "
            f"row(s), attainment {s['attainment']}, "
            f"{s.get('breaches', 0)} breach(es), "
            f"{s.get('dropped_spans', 0)} dropped span(s)")
    return failures, report


def aot_key(result: dict) -> str:
    """Golden key for an aot_report: model + shape + dispatch formulation.
    EP rows (lowered at an expert mesh) extend the key with the degree and
    transport so replicated/a2a/a2a_overlap goldens coexist per shape;
    composed-topology rows (r22) append dp/pp/seq tokens when those axes
    are in the mesh, so every golden row is one (dp, ep, pp, seq) tuple.
    Single-axis rows keep their historical keys unchanged."""
    key = (f"{result['model']} b{result['per_chip_batch']} "
           f"s{result['seq_len']} {result.get('moe_dispatch_impl', '-')}")
    if int(result.get("ep_degree", 1) or 1) > 1:
        key += (f" ep{result['ep_degree']} "
                f"{result.get('moe_ep_dispatch', 'replicated')}")
    if int(result.get("dp_degree", 0) or 0) > 1:
        key += f" dp{result['dp_degree']}"
    if int(result.get("pp_degree", 1) or 1) > 1:
        key += f" pp{result['pp_degree']}"
    if int(result.get("seq_degree", 1) or 1) > 1:
        key += f" seq{result['seq_degree']}"
    return key


def check_aot_bytes(result: dict, golden: dict, tolerance: float = 0.10):
    """Gate per-region AOT modeled bytes against golden.json ``aot_regions``.

    Unlike throughput (lower = regression), modeled bytes regress UPWARD:
    a region fails when its gbytes_modeled exceeds the golden by more than
    ``tolerance``. Shrinking is always fine — improvements re-record.
    Goldens are specific to the lowering backend (XLA:CPU fuses differently
    from TPU) and to the fusion-attribution model, so a mismatch on either
    field skips the comparison rather than failing on incomparable numbers.
    """
    failures, report = [], []
    key = aot_key(result)
    entry = golden.get("aot_regions", {}).get(key)
    if not entry:
        report.append(f"NO-GOLDEN aot_regions[{key}]: record with --record")
        return failures, report
    for field in ("backend_lowering", "attribution"):
        if entry.get(field) != result.get(field):
            report.append(
                f"SKIP aot_regions[{key}]: {field} mismatch "
                f"(golden {entry.get(field)!r}, result {result.get(field)!r})")
            return failures, report
    for region, ref in sorted(entry["regions"].items()):
        row = result.get("regions", {}).get(region)
        if row is None:
            report.append(f"NO-REGION {region} ({key}): absent from result")
            continue
        val = float(row["gbytes_modeled"])
        ratio = val / ref if ref else (float("inf") if val else 1.0)
        line = (f"aot_bytes {region} ({key}): {val:.3f} GB vs golden "
                f"{ref:.3f} GB ({ratio:.2%})")
        if ratio > 1.0 + tolerance:
            failures.append(line)
            report.append("REGRESSION " + line)
        else:
            report.append("OK " + line)
    # Memory census (r22): the abstract lowering's per-device high-water
    # regresses UPWARD like traffic. Only temps + resident are gated —
    # argument bytes are a function of the param count and sharding, which
    # the regions gate already pins transitively.
    mem = result.get("memory")
    ref_mem = entry.get("memory")
    if mem and ref_mem:
        for field in ("temp_bytes", "resident_bytes"):
            if ref_mem.get(field) is None or mem.get(field) is None:
                continue
            val, ref = float(mem[field]), float(ref_mem[field])
            ratio = val / ref if ref else (float("inf") if val else 1.0)
            line = (f"aot_memory {field} ({key}): {val / 1e6:.1f} MB vs "
                    f"golden {ref / 1e6:.1f} MB ({ratio:.2%})")
            if ratio > 1.0 + tolerance:
                failures.append(line)
                report.append("REGRESSION " + line)
            else:
                report.append("OK " + line)
    # Sequence-parallel shrink gate (r22): the point of the context axis is
    # that per-device activation temps scale ~1/seq (ring attention never
    # materializes the full [S, S] score block and every residual tensor is
    # [B, S/seq, d]). A seq row must undercut its seq=1 sibling golden by at
    # least half the ideal scaling — val * seq <= ref * 2.0 — or the sharded
    # lowering has stopped paying for its collectives.
    seq = int(result.get("seq_degree", 1) or 1)
    if mem and seq > 1:
        sib_key = aot_key({**result, "seq_degree": 1})
        sib = golden.get("aot_regions", {}).get(sib_key, {}).get("memory")
        if sib is None or sib.get("temp_bytes") is None:
            report.append(f"NO-GOLDEN aot_regions[{sib_key}]: record the "
                          "seq=1 sibling to arm the seq-shrink gate")
        else:
            val, ref = float(mem["temp_bytes"]), float(sib["temp_bytes"])
            line = (f"aot_seq_shrink ({key}): temp bytes {val / 1e6:.1f} MB "
                    f"x seq{seq} vs seq1 golden {ref / 1e6:.1f} MB")
            if val * seq > ref * 2.0:
                failures.append(line + " — per-device activation temps no "
                                "longer shrink ~1/seq")
                report.append("REGRESSION " + line)
            else:
                report.append("OK " + line)
    # EP comms model (r17): collective moe bytes regress upward like any
    # traffic number, and an a2a row must also UNDERCUT its replicated
    # sibling golden at the same shape/degree — the whole point of sharding
    # the dropless path is that token shards cost less than weight gathers,
    # so losing that inequality is a regression even inside tolerance.
    coll = result.get("collectives")
    ref_coll = entry.get("collectives")
    if coll and ref_coll and ref_coll.get("moe_bytes") is not None:
        val, ref = float(coll["moe_bytes"]), float(ref_coll["moe_bytes"])
        ratio = val / ref if ref else (float("inf") if val else 1.0)
        line = (f"aot_collective_moe_bytes ({key}): {val / 1e6:.3f} MB vs "
                f"golden {ref / 1e6:.3f} MB ({ratio:.2%})")
        if ratio > 1.0 + tolerance:
            failures.append(line)
            report.append("REGRESSION " + line)
        else:
            report.append("OK " + line)
    ep_dispatch = result.get("moe_ep_dispatch", "replicated")
    if (coll and int(result.get("ep_degree", 1) or 1) > 1
            and ep_dispatch != "replicated"):
        rep_key = aot_key({**result, "moe_ep_dispatch": "replicated"})
        rep = golden.get("aot_regions", {}).get(rep_key, {}).get("collectives")
        if rep is None:
            report.append(f"NO-GOLDEN aot_regions[{rep_key}]: record the "
                          "replicated sibling to arm the a2a<replicated gate")
        else:
            val, ref = float(coll["moe_bytes"]), float(rep["moe_bytes"])
            line = (f"aot_ep_comms ({key}): moe collective bytes "
                    f"{val / 1e6:.3f} MB vs replicated golden "
                    f"{ref / 1e6:.3f} MB")
            if val >= ref:
                failures.append(line + " — a2a no longer undercuts "
                                "replicated weight gathers")
                report.append("REGRESSION " + line)
            else:
                report.append("OK " + line)
    return failures, report


def record_aot_golden(result: dict, path: str = GOLDEN_PATH) -> str:
    """Write a report's per-region bytes as the golden entry (full-file
    rewrite: golden.json is small and hand-tended)."""
    with open(path) as fh:
        golden = json.load(fh)  # keep "_"-prefixed comment keys
    entry = {
        "backend_lowering": result.get("backend_lowering"),
        "attribution": result.get("attribution"),
        "regions": {tag: row["gbytes_modeled"]
                    for tag, row in result.get("regions", {}).items()},
    }
    if result.get("xla_flops_per_step") is not None:
        entry["xla_flops_per_step"] = result["xla_flops_per_step"]
    if result.get("memory"):
        entry["memory"] = dict(result["memory"])
    coll = result.get("collectives")
    if coll:
        entry["collectives"] = {
            "total_bytes": coll["total_bytes"],
            "moe_bytes": coll["moe_bytes"],
            "by_opcode": {op: row["bytes"]
                          for op, row in coll.get("by_opcode", {}).items()},
        }
    golden.setdefault("aot_regions", {})[aot_key(result)] = entry
    with open(path, "w") as fh:
        json.dump(golden, fh, indent=2)
        fh.write("\n")
    return aot_key(result)


def check_lint(root=None, baseline=None, ir_model=None):
    """Run graftlint (AST layer; optionally one IR lowering) as a gate.

    Fails on any unbaselined error-severity finding; stale suppressions are
    reported but do not fail (the code they covered moved — refresh with
    ``--record``).
    """
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import graftlint

    findings = graftlint.run_ast(root or graftlint.REPO_ROOT)
    if ir_model:
        findings += graftlint.run_ir(ir_model)
    doc = graftlint.load_baseline(baseline or graftlint.DEFAULT_BASELINE)
    unbaselined, baselined, stale = graftlint.split_findings(findings, doc)
    failures, report = [], []
    for f in findings:
        if f in baselined:
            report.append(f"LINT-BASELINED {f.render()}")
        elif f.severity == graftlint.ERROR:
            failures.append(f.render())
            report.append(f"LINT-FAIL {f.render()}")
        else:
            report.append(f"LINT-INFO {f.render()}")
    for s in stale:
        report.append(f"LINT-STALE suppression no longer matches: "
                      f"{s.get('rule')} {s.get('path')} {s.get('scope')}")
    report.append(f"LINT {len(findings)} finding(s), {len(baselined)} "
                  f"baselined, {len(failures)} unbaselined error(s), "
                  f"{len(stale)} stale")
    return failures, report, findings


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("result", nargs="?", help="bench JSON file (default: stdin)")
    p.add_argument("--tolerance", type=float, default=0.10)
    p.add_argument("--metrics-jsonl", default=None,
                   help="also scan this run's metrics.jsonl for non-finite "
                        "training-health scalars (telemetry rows); any hit "
                        "fails the gate")
    p.add_argument("--goodput", default=None, metavar="GOODPUT_JSON",
                   help="also gate this run's goodput.json on span coverage "
                        "(cumulative across supervisor attempts for elastic "
                        "runs); fails below --goodput-min-coverage")
    p.add_argument("--goodput-min-coverage", type=float, default=0.95)
    p.add_argument("--ttfs", default=None, metavar="GOODPUT_JSON",
                   help="also gate warm-restart time-to-first-step from "
                        "this goodput.json's ttfs_history: every warm "
                        "(executable-cache hit) attempt must come in under "
                        "--ttfs-max-ratio of the slowest cold compile; "
                        "neutral when the run has no warm/cold pair "
                        "(missing or quarantined cache = cold-only = OK)")
    p.add_argument("--ttfs-max-ratio", type=float, default=0.8)
    p.add_argument("--slo", default=None, metavar="SLO_JSONL",
                   help="also gate this serving run's slo.jsonl "
                        "(serve/slo.py): well-formed rows, single run_id, "
                        "window coverage, finite quantiles")
    p.add_argument("--cluster", action="store_true",
                   help="with --goodput: the file is a fleet "
                        "cluster_goodput.json (launch.py --fleet) — gate "
                        "wall-weighted coverage across jobs and accept the "
                        "distinct per-job run_ids a multi-tenant aggregate "
                        "carries by construction")
    p.add_argument("--aot-bytes", action="store_true",
                   help="input is a profile_step.py --aot report: gate "
                        "per-region modeled bytes (UP is the regression "
                        "direction) against golden.json aot_regions; runs "
                        "without a chip")
    p.add_argument("--record", action="store_true",
                   help="with --aot-bytes: write the report's regions as "
                        "the golden entry instead of comparing; with "
                        "--lint: refresh the suppression baseline (new "
                        "entries land as UNREVIEWED)")
    p.add_argument("--lint", action="store_true",
                   help="run graftlint (AST layer) as a gate: fail on any "
                        "unbaselined error finding; chip-free and jax-free")
    p.add_argument("--lint-ir", default=None, metavar="MODEL",
                   help="with --lint: also IR-lint MODEL's abstract "
                        "lowering (donation/precision/host-transfer/"
                        "sharding rules; needs jax)")
    p.add_argument("--lint-root", default=None,
                   help="with --lint: lint this tree instead of the repo "
                        "(fixture testing)")
    p.add_argument("--lint-baseline", default=None,
                   help="with --lint: suppression file (default "
                        "benchmarks/lint_baseline.json)")
    args = p.parse_args(argv)
    failures, report = [], []
    if args.lint:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        l_failures, l_report, findings = check_lint(
            args.lint_root, args.lint_baseline, args.lint_ir)
        if args.record:
            import graftlint

            graftlint.record_baseline(
                findings, args.lint_baseline or graftlint.DEFAULT_BASELINE)
            print("RECORDED lint baseline "
                  f"({sum(1 for f in findings if f.severity == graftlint.ERROR)} "
                  "suppression(s); review any UNREVIEWED entries)")
            return 0
        for line in l_report:
            print(line)
        return 1 if l_failures else 0
    if args.aot_bytes:
        raw = open(args.result).read() if args.result else sys.stdin.read()
        try:
            data = json.loads(raw)
        except json.JSONDecodeError:
            data = json.loads(raw.strip().splitlines()[-1])
        result = data.get("parsed", data)
        if args.record:
            key = record_aot_golden(result)
            print(f"RECORDED aot_regions[{key}]")
            return 0
        failures, report = check_aot_bytes(result, load_golden(),
                                           args.tolerance)
        for line in report:
            print(line)
        return 1 if failures else 0
    # --metrics-jsonl / --goodput / --slo alone are standalone scans (no
    # bench row expected on stdin); a positional result file, or plain piped
    # usage, still runs the golden comparison.
    if args.result or not (args.metrics_jsonl or args.goodput or args.slo
                           or args.ttfs):
        raw = open(args.result).read() if args.result else sys.stdin.read()
        # Accept a driver BENCH_r{N}.json wrapper (pretty-printed, result
        # under "parsed") or piped bench.py output (last stdout line is the
        # JSON).
        try:
            data = json.loads(raw)
        except json.JSONDecodeError:
            data = json.loads(raw.strip().splitlines()[-1])
        result = data.get("parsed", data)
        failures, report = check(result, load_golden(), args.tolerance)
    if args.metrics_jsonl:
        h_failures, h_report = check_health(args.metrics_jsonl)
        failures += h_failures
        report += h_report
    if args.goodput:
        g_failures, g_report = check_goodput(args.goodput,
                                             args.goodput_min_coverage,
                                             cluster=args.cluster)
        failures += g_failures
        report += g_report
    if args.ttfs:
        t_failures, t_report = check_ttfs(args.ttfs, args.ttfs_max_ratio)
        failures += t_failures
        report += t_report
    if args.slo:
        s_failures, s_report = check_slo(args.slo)
        failures += s_failures
        report += s_report
    for line in report:
        print(line)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
