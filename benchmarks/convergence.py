#!/usr/bin/env python
"""Convergence artifact (VERDICT r3 missing #1).

The reference's implicit acceptance test is "ResNet converges to known
accuracy" (SURVEY.md §4.4). Real CIFAR/ImageNet files and network access
don't exist in this environment, so this is the longest-horizon proxy
available: train the reference dev config (ResNet-18, 32px, 10 classes —
the CIFAR-10 preset's synthetic fallback, a deterministic pattern+noise
task) until held-out accuracy crosses a threshold, and record the full
accuracy-vs-epoch curve as CONVERGENCE.json.

    python benchmarks/convergence.py --threshold 0.9 --out CONVERGENCE.json

Runs on CPU fake devices by default (CI-runnable, no TPU needed).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--steps-per-epoch", type=int, default=40)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--model", default="resnet18")
    p.add_argument("--threshold", type=float, default=0.9)
    p.add_argument("--out", default="CONVERGENCE.json")
    p.add_argument("--tpu", action="store_true",
                   help="run on the default backend instead of CPU fakes")
    args = p.parse_args(argv)

    if not args.tpu:
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from pytorch_distributed_training_example_tpu.core.trainer import Trainer
    from pytorch_distributed_training_example_tpu.utils.config import from_preset

    cfg = from_preset(
        "resnet18_cifar10", model=args.model, global_batch_size=args.batch_size,
        epochs=args.epochs, steps_per_epoch=args.steps_per_epoch,
        lr=args.lr, workers=0, evaluate=True, eval_every_epochs=1,
        checkpoint_dir=tempfile.mkdtemp(prefix="conv_ck_"))
    t = Trainer(cfg)

    curve = []
    t0 = time.time()
    reached = None
    for epoch in range(cfg.epochs):
        t.train_epoch(epoch)
        avg = t.evaluate(epoch)
        row = {"epoch": epoch, "step": int(t.state.step),
               "acc_top1": round(avg.get("acc_top1", 0.0), 4),
               "acc_top5": round(avg.get("acc_top5", 0.0), 4),
               "loss": round(avg.get("loss", 0.0), 4),
               "wall_s": round(time.time() - t0, 1)}
        curve.append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)
        if reached is None and row["acc_top1"] >= args.threshold:
            reached = epoch
            break  # artifact complete: threshold crossed
    t.metric_logger.close()

    out = {
        "task": ("synthetic CIFAR-10-shaped 10-class pattern+noise "
                 "(data/datasets.py SyntheticImageDataset; eval on the "
                 "held-out split of the same distribution)"),
        "model": args.model,
        "global_batch": args.batch_size,
        "steps_per_epoch": args.steps_per_epoch,
        "lr": args.lr,
        "devices": jax.device_count(),
        "backend": jax.default_backend(),
        "threshold": args.threshold,
        "reached_at_epoch": reached,
        "final_acc_top1": curve[-1]["acc_top1"] if curve else 0.0,
        "ok": reached is not None,
        "curve": curve,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: out[k] for k in
                      ("final_acc_top1", "reached_at_epoch", "ok")}))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
