#!/usr/bin/env python
"""Convergence artifact (VERDICT r3 missing #1; hardened in r5 per r4 weak #4).

The reference's implicit acceptance test is "ResNet converges to known
accuracy" (SURVEY.md §4.4). Real CIFAR/ImageNet files and network access
don't exist in this environment, so this is the longest-horizon proxy
available: train the reference dev config (ResNet-18, 32px, 10 classes —
the CIFAR-10 preset's synthetic fallback, a deterministic pattern+noise
task) and record the full accuracy-vs-epoch curve as CONVERGENCE.json.

r5 hardening (the r4 artifact was a 2-point curve on an eval split that
reused the train noise stream):

- the eval split draws a DISJOINT per-sample noise stream (genuinely
  held-out; ``SyntheticImageDataset.noise_seed``);
- train-time augmentation is ON (reflect-pad-4 crop + flip — the CIFAR
  recipe), so the run measures learning under the reference transform,
  not memorization of fixed tensors;
- the curve runs the FULL horizon (no early stop): >= 5 points;
- a seen-samples/no-augment evaluation accompanies every epoch, and the
  final train/eval generalization gap is recorded and bounded.

A-priori acceptance (asserted by tests/test_convergence.py): held-out
top-1 >= 0.90 by the final epoch, and |seen - heldout| <= 0.10.

``--task lm`` (r17) is the LM counterpart with an ENTROPY-FLOOR gate
instead of an accuracy threshold. The synthetic LM stream
(``SyntheticTokenDataset``) draws tokens i.i.d. uniform over the vocab,
so the best achievable next-token loss is exactly ``ln(vocab_size)``
nats/token (6.2383 for llama_tiny's vocab of 512) — no model can beat
it without cheating. The gate is two-sided:

- final eval loss <= floor + margin: the optimizer actually drove the
  randomly-initialized logits down to the entropy floor (training and
  the loss plumbing work);
- final eval loss >= floor - eps: a loss BELOW the floor on i.i.d.
  uniform data is impossible except through target leakage — a broken
  causal mask (attention peeking at position t+1) or shifted-target
  misalignment. This is the cheap, always-on canary for exactly the bug
  class the r17 EP dispatch reshuffles tokens around.

    python benchmarks/convergence.py --out CONVERGENCE.json
    python benchmarks/convergence.py --task lm --out CONVERGENCE_LM.json

Runs on CPU fake devices by default (CI-runnable, no TPU needed).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time


def run_lm(args):
    """LM entropy-floor leg: train ``--model`` (llama_tiny default; pass
    llama_moe_tiny + --moe-* mains for the MoE path) on the uniform
    synthetic token stream and gate the final eval loss against
    ``ln(vocab_size)``."""
    import jax

    from pytorch_distributed_training_example_tpu.core.trainer import Trainer
    from pytorch_distributed_training_example_tpu.utils.config import Config

    model = args.model if args.model != "resnet18" else "llama_tiny"
    cfg = Config(
        model=model, dataset="lm", seq_len=args.seq_len,
        global_batch_size=args.batch_size, epochs=args.epochs,
        steps_per_epoch=args.steps_per_epoch, lr=args.lr,
        warmup_epochs=0.0, optimizer="adamw", weight_decay=0.0,
        precision="fp32", workers=0, evaluate=True, eval_every_epochs=1,
        moe_dispatch_impl=args.moe_dispatch,
        moe_capacity_factor=1.0 if args.moe_dispatch == "dropless" else 1.25,
        moe_ep_dispatch=args.moe_ep_dispatch,
        checkpoint_dir=tempfile.mkdtemp(prefix="conv_lm_ck_"))
    t = Trainer(cfg)
    vocab = getattr(t.bundle.module, "vocab_size", None)
    assert vocab, f"{model} exposes no vocab_size; cannot place the floor"
    floor = math.log(vocab)

    curve = []
    t0 = time.time()
    for epoch in range(cfg.epochs):
        t.train_epoch(epoch)
        avg = t.evaluate(epoch)
        row = {"epoch": epoch, "step": int(t.state.step),
               "loss": round(avg.get("loss", float("nan")), 4),
               "wall_s": round(time.time() - t0, 1)}
        curve.append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)
    t.metric_logger.close()

    final_loss = curve[-1]["loss"] if curve else float("nan")
    out = {
        "task": ("synthetic LM, tokens i.i.d. uniform over the vocab "
                 "(data/datasets.py SyntheticTokenDataset) — entropy floor "
                 "= ln(vocab) exactly; loss below the floor implies target "
                 "leakage (causal mask / target shift)"),
        "model": model,
        "vocab_size": vocab,
        "entropy_floor_nats": round(floor, 4),
        "floor_margin": args.floor_margin,
        "floor_eps": args.floor_eps,
        "seq_len": args.seq_len,
        "global_batch": args.batch_size,
        "steps_per_epoch": args.steps_per_epoch,
        "epochs": args.epochs,
        "lr": args.lr,
        "moe_dispatch_impl": args.moe_dispatch,
        "moe_ep_dispatch": args.moe_ep_dispatch,
        "devices": jax.device_count(),
        "backend": jax.default_backend(),
        "final_loss": final_loss,
        "ok": (final_loss == final_loss  # NaN guard
               and floor - args.floor_eps <= final_loss
               <= floor + args.floor_margin),
        "curve": curve,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: out[k] for k in
                      ("final_loss", "entropy_floor_nats", "ok")}))
    return 0 if out["ok"] else 1


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--task", default="vision", choices=["vision", "lm"],
                   help="vision: ResNet accuracy-threshold artifact; lm: "
                        "LM entropy-floor gate on the uniform token stream")
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--steps-per-epoch", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--model", default="resnet18")
    p.add_argument("--threshold", type=float, default=0.9)
    p.add_argument("--max-gap", type=float, default=0.10)
    p.add_argument("--seq-len", type=int, default=64,
                   help="--task lm: sequence length")
    p.add_argument("--floor-margin", type=float, default=0.10,
                   help="--task lm: final loss may sit this far ABOVE "
                        "ln(vocab) (optimizer still closing in)")
    p.add_argument("--floor-eps", type=float, default=1e-3,
                   help="--task lm: loss below floor - eps fails (target "
                        "leakage; fp sum tolerance only)")
    p.add_argument("--moe-dispatch", default="gather",
                   choices=["sort", "gather", "einsum", "dropless"],
                   help="--task lm with an MoE model")
    p.add_argument("--moe-ep-dispatch", default="replicated",
                   choices=["replicated", "a2a", "a2a_overlap"],
                   help="--task lm with an MoE model (dropless only)")
    p.add_argument("--out", default=None)
    p.add_argument("--tpu", action="store_true",
                   help="run on the default backend instead of CPU fakes")
    args = p.parse_args(argv)
    if args.out is None:
        args.out = ("CONVERGENCE_LM.json" if args.task == "lm"
                    else "CONVERGENCE.json")

    if not args.tpu:
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if args.task == "lm":
        if args.epochs == 10 and args.steps_per_epoch == 30:
            # vision defaults are oversized for the floor gate; the LM leg
            # converges to ln(V) in a few hundred small-batch steps
            args.epochs, args.steps_per_epoch = 5, 40
        if args.batch_size == 128:
            args.batch_size = 16
        if args.lr == 0.05:
            args.lr = 1e-3
        return run_lm(args)

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
    from pytorch_distributed_training_example_tpu.core.trainer import Trainer
    from pytorch_distributed_training_example_tpu.data import (
        datasets as datasets_lib, loader as loader_lib, prefetch)
    from pytorch_distributed_training_example_tpu.utils import (
        metrics as metrics_lib)
    from pytorch_distributed_training_example_tpu.utils.config import from_preset

    # Record every consumed train index (the mid-epoch-resume debug hook)
    # so the seen-samples probe scores indices the optimizer REALLY
    # trained on — with a 51,200-sample shuffled pool and 30×128 consumed
    # per epoch, fixed probe indices would be mostly never-trained and the
    # gap bound near-vacuous (r5 review finding).
    idx_log = os.path.join(tempfile.mkdtemp(prefix="conv_idx_"), "idx.jsonl")

    cfg = from_preset(
        "resnet18_cifar10", model=args.model, global_batch_size=args.batch_size,
        epochs=args.epochs, steps_per_epoch=args.steps_per_epoch,
        lr=args.lr, workers=0, evaluate=True, eval_every_epochs=1,
        checkpoint_dir=tempfile.mkdtemp(prefix="conv_ck_"))
    t = Trainer(cfg)
    assert getattr(t.train_data, "augment", False), \
        "convergence run must train under augmentation"
    assert t.eval_data.noise_seed != t.train_data.noise_seed, \
        "eval split must be disjoint from the train noise stream"

    # Un-augmented view of the train distribution for the probe (the gap
    # is measured under eval transforms, like CIFAR practice).
    seen_ds = datasets_lib.SyntheticImageDataset(
        len(t.train_data), cfg.image_size, cfg.num_classes, cfg.seed,
        augment=False)

    def trained_indices():
        """Unique sample indices consumed by TRAINED steps (the loader
        overfetches a few batches past the steps-per-epoch cap; batches
        beyond the cap are dropped here)."""
        seen = []
        have = set()
        with open(idx_log) as fh:
            for line in fh:
                row = json.loads(line)
                if row["batch"] >= args.steps_per_epoch:
                    continue
                for i in row["indices"]:
                    if i not in have:
                        have.add(i)
                        seen.append(i)
        return seen

    def eval_seen(max_samples=2048):
        idx = trained_indices()[-max_samples:]
        sums = {}
        with mesh_lib.use_mesh(t.mesh):
            batches = (loader_lib.collate([seen_ds[i] for i in
                                           idx[j: j + t.local_batch]])
                       for j in range(0, len(idx) - t.local_batch + 1,
                                      t.local_batch))
            for batch in prefetch.device_prefetch(batches, t.batch_sharding):
                stats = t.eval_step(t.state, batch)
                for k, v in jax.device_get(stats).items():
                    sums[k] = sums.get(k, 0.0) + float(v)
        return metrics_lib.finalize_eval_sums(sums)

    curve = []
    t0 = time.time()
    reached = None
    for epoch in range(cfg.epochs):
        # The index log must record TRAIN consumption only — every
        # DataLoader in the process honors the env var, and evaluate()'s
        # eval-split batches would otherwise pollute trained_indices()
        # with never-trained samples (r5 review finding). Toggle it
        # around the phases; all loaders here are consumed synchronously.
        # try/finally so a raising train_epoch (OOM, fault injection)
        # can't leak the env var into the eval phase or the next run.
        os.environ[loader_lib.INDEX_LOG_ENV] = idx_log
        try:
            t.train_epoch(epoch)
        finally:
            os.environ.pop(loader_lib.INDEX_LOG_ENV, None)
        avg = t.evaluate(epoch)
        seen = eval_seen()
        row = {"epoch": epoch, "step": int(t.state.step),
               "acc_top1": round(avg.get("acc_top1", 0.0), 4),
               "acc_top5": round(avg.get("acc_top5", 0.0), 4),
               "loss": round(avg.get("loss", 0.0), 4),
               "seen_acc_top1": round(seen.get("acc_top1", 0.0), 4),
               "gap": round(seen.get("acc_top1", 0.0)
                            - avg.get("acc_top1", 0.0), 4),
               "wall_s": round(time.time() - t0, 1)}
        curve.append(row)
        print(json.dumps(row), file=sys.stderr, flush=True)
        if reached is None and row["acc_top1"] >= args.threshold:
            reached = epoch
    t.metric_logger.close()

    final = curve[-1] if curve else {}
    out = {
        "task": ("synthetic CIFAR-10-shaped 10-class pattern+noise, "
                 "augmented train (pad-4 crop + flip), eval on a DISJOINT "
                 "noise stream of the same pattern distribution "
                 "(data/datasets.py SyntheticImageDataset noise_seed)"),
        "model": args.model,
        "global_batch": args.batch_size,
        "steps_per_epoch": args.steps_per_epoch,
        "epochs": args.epochs,
        "lr": args.lr,
        "devices": jax.device_count(),
        "backend": jax.default_backend(),
        "threshold": args.threshold,
        "max_gap": args.max_gap,
        "reached_at_epoch": reached,
        "final_acc_top1": final.get("acc_top1", 0.0),
        "final_seen_acc_top1": final.get("seen_acc_top1", 0.0),
        "generalization_gap": final.get("gap", 1.0),
        # acceptance = the stated a-priori rule: held-out accuracy at the
        # FINAL epoch (late regression must fail, matching the artifact
        # test), plus the bounded train/eval gap.
        "ok": (final.get("acc_top1", 0.0) >= args.threshold
               and abs(final.get("gap", 1.0)) <= args.max_gap),
        "curve": curve,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: out[k] for k in
                      ("final_acc_top1", "generalization_gap",
                       "reached_at_epoch", "ok")}))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
