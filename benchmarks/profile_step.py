#!/usr/bin/env python
"""Per-HLO step profile from an xplane trace (VERDICT r3 #1/#3 tooling).

Profiles the SAME compiled train step bench.py times (shared setup via
``bench.setup_step``), then parses the ``jax.profiler`` xplane dump into a
per-op table and category rollup — the methodology behind PROFILE_GPT2.md /
PROFILE_RN50.md, now a reusable script instead of a throwaway:

    python benchmarks/profile_step.py --model vit_b16 --per-chip-batch 64 \
        --out PROFILE_VIT.json

Classification is NOT name-guessing: the compiled module's HLO text is
parsed so every fusion is categorized by what its called computation
actually contains (convolution > dot > scatter > reduce > elementwise,
first match wins), and trace events are joined to that map by op name.
Durations are measured device time — no cost model in the loop.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Category priority (first present wins) for a fused computation's body.
_PRIORITY = ["attention_kernel", "conv", "matmul", "scatter", "gather",
             "pool", "reduce"]


def _body_category(body: str) -> str:
    found = set()
    for line in body.splitlines():
        if "tpu_custom_call" in line or "mosaic" in line:
            found.add("attention_kernel")
        elif " convolution(" in line:
            # XLA:TPU lowers big dot_generals to convolution instructions;
            # the source metadata tells them apart from real convs.
            found.add("matmul" if "dot_general" in line else "conv")
        elif " dot(" in line:
            found.add("matmul")
        elif " scatter(" in line:
            found.add("scatter")
        elif " gather(" in line:
            found.add("gather")
        elif " reduce-window(" in line:
            found.add("pool")
        elif " reduce(" in line:
            found.add("reduce")
    for cat in _PRIORITY:
        if cat in found:
            return cat
    return "elementwise"


def _src_tag(line: str) -> str | None:
    """Short source tag from metadata: last path components of op_name."""
    m = re.search(r'op_name="([^"]+)"', line)
    if not m:
        return None
    return "/".join(m.group(1).split("/")[-3:])


def build_op_categories(hlo_text: str):
    """Map every instruction name -> category using computation contents."""
    # Split into computations: "%name (args) -> ret {\n ... \n}"
    comp_bodies = {}
    for m in re.finditer(r"^(?:ENTRY )?%?([\w.\-]+)(?:\.clone)? \([^)]*\) -> .*? \{\n(.*?)^\}",
                         hlo_text, re.M | re.S):
        comp_bodies[m.group(1)] = m.group(2)
    comp_cat = {name: _body_category(body)
                for name, body in comp_bodies.items()}

    op_cat = {}
    op_src = {}
    for name, body in comp_bodies.items():
        for line in body.splitlines():
            # Result shapes may be tuples with spaces and one level of
            # nested parens from layouts (T(8,128), S(1)); the opcode is
            # the first lowercase token directly before a '(' after '='.
            im = re.match(
                r"\s+(?:ROOT )?%?([\w.\-]+) = .*?([a-z][a-z0-9\-]*)\(", line)
            if not im:
                continue
            op, opcode = im.group(1), im.group(2)
            src = _src_tag(line)
            if src:
                op_src[op] = src
            if opcode == "fusion":
                cm = re.search(r"calls=%?([\w.\-]+)", line)
                op_cat[op] = comp_cat.get(cm.group(1), "elementwise") \
                    if cm else "elementwise"
            elif opcode == "custom-call":
                op_cat[op] = ("attention_kernel"
                              if "tpu_custom_call" in line else "custom_call")
            elif opcode == "convolution":
                op_cat[op] = "matmul" if "dot_general" in line else "conv"
            elif opcode == "dot":
                op_cat[op] = "matmul"
            elif opcode in ("all-reduce", "all-gather", "reduce-scatter",
                            "all-to-all", "collective-permute"):
                op_cat[op] = "collective"
            elif opcode.startswith("copy") or opcode in ("bitcast", "convert",
                                                         "transpose", "reshape"):
                op_cat[op] = "copy_layout"
            else:
                op_cat[op] = opcode
    return op_cat, op_src


# MoE step regions tagged with jax.named_scope in parallel/moe.py. The tag
# survives into op_name metadata for forward ops ("...moe_dispatch/...") and
# for their cotangents (jax keeps the scope path inside transpose(...)), so
# a rollup by tag attributes fwd+bwd time per region. The dropless kernel
# (ops/grouped_matmul.py) tags its pallas calls moe_experts_gmm; nested
# under moe_experts the leftmost match wins (bytes stay comparable across
# dispatch impls), while kernel ops whose scope stack XLA rewrote down to
# the inner tag still classify instead of leaking into non_moe.
_MOE_TAG_RE = re.compile(
    r"\bmoe_(router|dispatch|experts_gmm|experts|combine|aux)\b")


def _moe_tag(line: str, tag_re: re.Pattern | None = None) -> str | None:
    """Region tag of one HLO line. Default: the MoE named-scope tags.
    ``tag_re`` swaps in another scope-tag alphabet (e.g. the serve_* tags
    of the decode step — benchmarks/serve_bench.py) and attributes by the
    full match text."""
    m = re.search(r'op_name="([^"]+)"', line)
    if not m:
        return None
    if tag_re is not None:
        t = tag_re.search(m.group(1))
        return t.group(0) if t else None
    t = _MOE_TAG_RE.search(m.group(1))
    return f"moe_{t.group(1)}" if t else None


def build_op_moe_tags(hlo_text: str, tag_re: re.Pattern | None = None):
    """Map instruction name -> MoE step region (moe_router / moe_dispatch /
    moe_experts / moe_combine / moe_aux) from the named-scope tags in
    op_name metadata. A fusion is attributed to the tag the majority of its
    fused instructions carry (mixed fusions happen at region boundaries);
    untagged instructions are absent from the map."""
    comp_bodies = {}
    for m in re.finditer(r"^(?:ENTRY )?%?([\w.\-]+)(?:\.clone)? \([^)]*\) -> .*? \{\n(.*?)^\}",
                         hlo_text, re.M | re.S):
        comp_bodies[m.group(1)] = m.group(2)
    comp_tags: dict[str, collections.Counter] = {}
    for name, body in comp_bodies.items():
        c = collections.Counter()
        for line in body.splitlines():
            t = _moe_tag(line, tag_re)
            if t:
                c[t] += 1
        comp_tags[name] = c

    op_moe = {}
    for name, body in comp_bodies.items():
        for line in body.splitlines():
            im = re.match(
                r"\s+(?:ROOT )?%?([\w.\-]+) = .*?([a-z][a-z0-9\-]*)\(", line)
            if not im:
                continue
            op, opcode = im.group(1), im.group(2)
            tag = _moe_tag(line, tag_re)
            if opcode == "fusion":
                cm = re.search(r"calls=%?([\w.\-]+)", line)
                cnt = comp_tags.get(cm.group(1)) if cm else None
                if cnt:
                    tag = cnt.most_common(1)[0][0]
            if tag:
                op_moe[op] = tag
    return op_moe


def build_op_moe_weights(hlo_text: str, tag_re: re.Pattern | None = None):
    """Map instruction name -> {region: fraction} for PROPORTIONAL byte
    attribution of mixed fusions.

    ``build_op_moe_tags`` is winner-take-all: a fusion goes to whichever
    region tags the most interior lines. That is right for the trace-timing
    path (a timed event is indivisible) but wrong for byte accounting on
    XLA:CPU, which builds whole-block backward mega-fusions (~900
    instructions) where a handful of tagged lines — e.g. 24 moe_router
    [T,d] cotangent converts vs 12 moe_dispatch lines, 96% untagged —
    decided the winner and charged the entire fusion's boundary traffic to
    one region (r7 recorded 125 GB of "router" bytes this way; the genuine
    router share is ~2.3x smaller).

    Here each fusion's bytes are split by the RESULT bytes of its tagged
    interior lines over all non-view interior result bytes; the untagged
    remainder stays unattributed (the caller charges it to non_moe).
    Fusions whose interior carries tags but zero bytes (scalar reducers)
    fall back to line majority. Non-fusion tagged instructions keep their
    own tag at weight 1.0. Fractions for an op sum to <= 1."""
    comp_bodies = {}
    for m in re.finditer(r"^(?:ENTRY )?%?([\w.\-]+)(?:\.clone)? \([^)]*\) -> .*? \{\n(.*?)^\}",
                         hlo_text, re.M | re.S):
        comp_bodies[m.group(1)] = m.group(2)
    line_re = re.compile(
        r"\s+(?:ROOT )?%?([\w.\-]+) = (.*?)([a-z][a-z0-9\-]*)\(")

    comp_frac: dict[str, dict[str, float]] = {}
    for name, body in comp_bodies.items():
        tag_bytes: collections.Counter = collections.Counter()
        tag_lines: collections.Counter = collections.Counter()
        total = 0
        for line in body.splitlines():
            im = line_re.match(line)
            if not im or im.group(3) in _VIEW_OPS:
                continue
            b = sum(_shape_bytes(dt, dims)
                    for dt, dims, _ in _SHAPE_LAYOUT_RE.findall(im.group(2)))
            total += b
            t = _moe_tag(line, tag_re)
            if t:
                tag_bytes[t] += b
                tag_lines[t] += 1
        if total:
            comp_frac[name] = {t: b / total for t, b in tag_bytes.items()}
        elif tag_lines:
            comp_frac[name] = {tag_lines.most_common(1)[0][0]: 1.0}

    op_w: dict[str, dict[str, float]] = {}
    for name, body in comp_bodies.items():
        for line in body.splitlines():
            im = line_re.match(line)
            if not im:
                continue
            op, opcode = im.group(1), im.group(3)
            if opcode == "fusion":
                cm = re.search(r"calls=%?([\w.\-]+)", line)
                w = comp_frac.get(cm.group(1)) if cm else None
                if w:
                    op_w[op] = w
                    continue
            t = _moe_tag(line, tag_re)
            if t:
                op_w[op] = {t: 1.0}
    return op_w


# Interpret-mode Pallas emulation: off-TPU, pallas_call lowers to an XLA
# while loop that walks the kernel grid, materializing every VMEM block
# move as a full-array dynamic-slice / dynamic-update-slice per grid step.
# On the real target the kernel is ONE custom call whose HBM traffic is
# its operands + results; the loop interior is pure CPU-lowering artifact
# (r14: it charged ~103 GB of phantom traffic to moe_experts for the
# dropless grouped matmul at the llama_moe bench shape). Interior ops
# carry the kernel's named scope followed by the loop path in op_name
# ("...moe_experts_gmm/while/body/..."); the while instruction itself
# (scope path ends at .../while) is KEPT — its carried tuple is the
# operand+result boundary, i.e. what a real custom call would be charged.
# Deliberately scoped to the dropless grouped-matmul kernel tag so rows
# recorded for non-Pallas impls are byte-identical under this rule.
_PALLAS_INTERIOR_RE = re.compile(r"\bmoe_experts_gmm/while/")


def build_pallas_interior(hlo_text: str):
    """Instruction names interior to an interpret-mode Pallas grid loop
    (``_PALLAS_INTERIOR_RE`` on op_name). ``aot_report`` drops them from
    the byte/op tabulation entirely — they do not exist on the target."""
    interior = set()
    for line in hlo_text.splitlines():
        m = re.match(r"\s+(?:ROOT )?%?([\w.\-]+) = ", line)
        if not m:
            continue
        nm = re.search(r'op_name="([^"]+)"', line)
        if nm and _PALLAS_INTERIOR_RE.search(nm.group(1)):
            interior.add(m.group(1))
    return interior


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
                "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8, "f8e4m3fn": 1, "f8e5m2": 1}

_DTYPE_PAT = (r"(?:pred|[us](?:8|16|32|64)|bf16|f(?:16|32|64)|"
              r"f8e4m3fn|f8e5m2)")
_SHAPE_RE = re.compile(rf"\b({_DTYPE_PAT})\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


# Zero-cost view/bookkeeping opcodes: no data movement of their own, and
# their results alias other buffers — counting them double-counts.
_VIEW_OPS = {"parameter", "constant", "get-tuple-element", "tuple", "bitcast"}

# Layout-aware shape: dims + optional {layout}; "S(<n>)" in the layout marks
# a buffer assigned to alternate memory space n (VMEM on TPU) — it never
# touches HBM. r4's accounting missed both directions here (ADVICE r4 +
# r5 re-derivation): operand lists in this XLA's as_text() are bare
# "%name" references (no inline shapes), so reads parsed as zero, while
# result bytes were counted even for views and VMEM-resident buffers.
_SHAPE_LAYOUT_RE = re.compile(rf"\b({_DTYPE_PAT})\[([\d,]*)\](\{{[^}}]*\}})?")


def build_op_bytes(hlo_text: str):
    """Per-instruction HBM traffic model from the scheduled module.

    Two passes. First, every instruction's name is mapped to its result
    buffer size (HBM portion only — tuple components whose layout carries
    an ``S(n)`` alternate-memory-space tag are excluded). Then each
    instruction is charged:

    - view/bookkeeping ops (parameter, constant, get-tuple-element, tuple,
      bitcast): 0 bytes;
    - ``*-start`` async halves: 0 (the transfer is charged to ``*-done``
      so a DMA is counted once, not twice);
    - everything else: its HBM result bytes (written) plus, for each
      UNIQUE operand name, that operand's HBM result bytes (read) — a
      buffer lookup, because operands appear as bare ``%name`` references.

    Unlike XLA's cost-model "bytes accessed" (which double-counts fused
    interior uses and can exceed physical bandwidth — VERDICT r3 weak #3)
    this approximates the DMA traffic the scheduled program issues. It is
    still a model: a tiled conv may re-read inputs (undercount) and a
    consumer whose producer stayed VMEM-resident is overcounted; the
    physical-peak sanity check lives with the caller's roofline."""
    line_re = re.compile(
        r"^\s+(?:ROOT )?%?([\w.\-]+) = (.*?)([a-z][a-z0-9\-]*)\((.*)$", re.M)
    info: dict[str, tuple[str, int, list[str]]] = {}
    for m in line_re.finditer(hlo_text):
        op, result_txt, opcode, rest = m.groups()
        # operands end where attributes begin
        for cut in (", kind=", ", calls=", ", metadata=", ", backend_config=",
                    ", custom_call_target=", ", dimensions=", ", window=",
                    ", to_apply=", ", condition=", ", body=", ", select=",
                    ", scatter=", ", control-predecessors=", ", sharding=",
                    ", frontend_attributes="):
            idx = rest.find(cut)
            if idx != -1:
                rest = rest[:idx]
        out_b = 0
        for dt, dims, layout in _SHAPE_LAYOUT_RE.findall(result_txt):
            if "S(" in (layout or ""):
                continue  # alternate memory space: not HBM traffic
            out_b += _shape_bytes(dt, dims)
        operands = re.findall(r"%([\w.\-]+)", rest)
        if not operands:
            # Some XLA versions print bare operand names without '%'
            # (the ADVICE-r4 fragility); fall back to comma-split tokens —
            # the caller filters them against the instruction map, which
            # rejects shape/attribute fragments.
            operands = [t.strip().split(" ")[-1].strip("()")
                        for t in rest.split(",") if t.strip()]
        info[op] = (opcode, out_b, operands)

    op_bytes = {}
    total_in = total_out = 0
    for op, (opcode, out_b, operands) in info.items():
        if opcode in _VIEW_OPS or opcode.endswith("-start"):
            op_bytes[op] = 0
            continue
        if opcode.endswith("-done"):
            op_bytes[op] = out_b  # one side of the DMA, counted once
            total_out += out_b
            continue
        in_b = 0
        seen = set()
        for name in operands:
            if name in seen:
                continue
            seen.add(name)
            oi = info.get(name)
            if oi is not None:
                in_b += oi[1]
        op_bytes[op] = in_b + out_b
        total_in += in_b
        total_out += out_b
    if total_out and total_in < 0.2 * total_out:
        # Reads should be comparable to writes across a module; a tiny
        # read term means the operand parse missed this dump's format and
        # the roofline is underreporting HBM traffic.
        print(f"WARNING: parsed operand-read bytes ({total_in/1e9:.2f} GB) "
              f"implausibly small vs result bytes ({total_out/1e9:.2f} GB) "
              "— HLO operand format likely unmatched; measured roofline "
              "will underreport traffic", file=sys.stderr)
    return op_bytes


# EP comms census: the collective opcodes whose result buffers carry the
# MoE transport cost (r17 ep_dispatch A/B). async -start halves are skipped
# and the transfer charged once at -done, matching build_op_bytes.
_COLLECTIVE_RE = re.compile(
    r"^(all-to-all|all-gather|all-reduce|reduce-scatter|collective-permute)"
    r"(-start|-done)?$")


def collective_byte_census(hlo_text: str):
    """Per-opcode / per-region byte census of the collectives in a compiled
    module — the chipless EP comms model (PROFILE_MOE.md r17).

    Every collective instruction is charged its HBM result-buffer bytes
    (``_shape_bytes`` over the printed result shape, alternate-memory
    components excluded) — a per-device transfer-volume proxy, not a wire
    model: an all-gather's result is the fully gathered buffer each device
    materializes, an all-to-all's is the shards it receives. That is the
    quantity the replicated-vs-a2a dropless decision trades (weight gathers
    vs token shards), so the rows are comparable across ``ep_dispatch``
    modes lowered at the same mesh. Attribution to MoE regions reuses the
    named-scope tags (``_moe_tag``); untagged collectives (grad psum over
    data axes, ...) land in ``non_moe``.

    Returns ``{"total_bytes", "moe_bytes", "by_opcode": {opcode: {"count",
    "bytes"}}, "by_region": {region: {"count", "bytes"}}}``. Counts are
    instruction-level (a collective inside a while body counts once).
    """
    line_re = re.compile(
        r"^\s+(?:ROOT )?%?([\w.\-]+) = (.*?)([a-z][a-z0-9\-]*)\(", re.M)
    by_opcode: dict[str, dict] = {}
    by_region: dict[str, dict] = {}
    total = moe = 0
    for m in line_re.finditer(hlo_text):
        _, result_txt, opcode = m.groups()
        cm = _COLLECTIVE_RE.match(opcode)
        if not cm or cm.group(2) == "-start":
            continue
        base = cm.group(1)
        b = 0
        for dt, dims, layout in _SHAPE_LAYOUT_RE.findall(result_txt):
            if "S(" in (layout or ""):
                continue
            b += _shape_bytes(dt, dims)
        line = hlo_text[m.start():hlo_text.find("\n", m.start())]
        region = _moe_tag(line) or "non_moe"
        o = by_opcode.setdefault(base, {"count": 0, "bytes": 0})
        o["count"] += 1
        o["bytes"] += b
        r = by_region.setdefault(region, {"count": 0, "bytes": 0})
        r["count"] += 1
        r["bytes"] += b
        total += b
        if region != "non_moe":
            moe += b
    return {"total_bytes": total, "moe_bytes": moe,
            "by_opcode": dict(sorted(by_opcode.items())),
            "by_region": dict(sorted(by_region.items(),
                                     key=lambda kv: -kv[1]["bytes"]))}


def collect_ops(trace_dir: str):
    """Aggregate XLA-op events across all device planes/steps in the dump."""
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True))
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    ops = collections.defaultdict(lambda: [0.0, 0])  # name -> [ns, count]
    module_ns = 0.0
    module_runs = 0
    for path in paths:
        pd = ProfileData.from_file(path)
        for plane in pd.planes:
            if not plane.name.startswith("/device:"):
                continue
            for line in plane.lines:
                if line.name == "XLA Modules":
                    for ev in line.events:
                        module_ns += ev.duration_ns
                        module_runs += 1
                if line.name != "XLA Ops":
                    continue
                for ev in line.events:
                    rec = ops[ev.name]
                    rec[0] += ev.duration_ns
                    rec[1] += 1
    return ops, module_ns, module_runs


def profile(model_name: str, *, image_size=224, per_chip_batch=64,
            precision="bf16", seq_len=1024, strategy=None, remat=False,
            remat_policy="nothing",
            attn_impl="auto", moe_capacity_factor=1.25, moe_top_k=2,
            moe_dispatch_impl="gather", moe_combine_dtype="fp32",
            moe_router_dtype="fp32", moe_router_impl="reference",
            moe_ep_dispatch="replicated", moe_ep_overlap_chunks=2,
            steps=3, trace_dir=None, top=25, telemetry=False):
    import jax

    from bench import setup_step
    from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
    from pytorch_distributed_training_example_tpu.utils import (
        metrics as metrics_lib)

    su = setup_step(model_name, image_size, per_chip_batch, precision,
                    seq_len, strategy=strategy, remat=remat,
                    remat_policy=remat_policy,
                    attn_impl=attn_impl,
                    moe_capacity_factor=moe_capacity_factor,
                    moe_top_k=moe_top_k,
                    moe_dispatch_impl=moe_dispatch_impl,
                    moe_combine_dtype=moe_combine_dtype,
                    moe_router_dtype=moe_router_dtype,
                    moe_router_impl=moe_router_impl,
                    moe_ep_dispatch=moe_ep_dispatch,
                    moe_ep_overlap_chunks=moe_ep_overlap_chunks,
                    telemetry=telemetry)
    mesh, state, step, batch = su["mesh"], su["state"], su["step"], su["batch"]
    bundle = su["bundle"]
    trace_dir = trace_dir or tempfile.mkdtemp(prefix="xprof_")
    with mesh_lib.use_mesh(mesh):
        compiled = jax.jit(step).lower(state, batch).compile()
        hlo_text = compiled.as_text()
        op_cat, op_src = build_op_categories(hlo_text)
        op_bytes = build_op_bytes(hlo_text)
        op_moe = build_op_moe_tags(hlo_text)
        state, m = compiled(state, batch)  # warm
        jax.tree.map(lambda x: x.block_until_ready(), m)
        jax.profiler.start_trace(trace_dir)
        for _ in range(steps):
            state, m = compiled(state, batch)
        jax.tree.map(lambda x: x.block_until_ready(), m)
        jax.profiler.stop_trace()

    ops, module_ns, module_runs = collect_ops(trace_dir)
    n_steps = module_runs or steps
    cats = collections.defaultdict(lambda: [0.0, 0, 0])  # ns, count, bytes
    moe_cats = collections.defaultdict(lambda: [0.0, 0, 0])
    rows = []
    total_ns = 0.0
    unmatched_ns = 0.0
    traffic_bytes = 0
    for name, (ns, count) in ops.items():
        nm = re.match(r"%?([\w.\-]+) =", name)
        op = nm.group(1) if nm else name
        cat = op_cat.get(op)
        if cat is None:
            cat = "unmatched"
            unmatched_ns += ns
        b = op_bytes.get(op, 0) * (count // max(n_steps, 1))
        cats[cat][0] += ns
        cats[cat][1] += count
        cats[cat][2] += b
        moe = op_moe.get(op, "non_moe")
        moe_cats[moe][0] += ns
        moe_cats[moe][1] += count
        moe_cats[moe][2] += b
        total_ns += ns
        traffic_bytes += b
        op_ms = ns / n_steps / 1e6
        rows.append({"ms_per_step": op_ms,
                     "count": count // n_steps, "category": cat,
                     "moe_region": op_moe.get(op),
                     "gbytes": round(b / 1e9, 3),
                     "gbps": round(b / (op_ms * 1e6), 1) if op_ms else 0.0,
                     "src": op_src.get(op), "hlo": name[:300]})
    rows.sort(key=lambda r: -r["ms_per_step"])
    # Per-category achieved bandwidth: category bytes over category device
    # time. For memory-bound categories (reduce, elementwise, copy_layout)
    # this is the sustained HBM rate; for MXU categories (conv, matmul) low
    # GB/s just means the time went to math, so read those rows together
    # with their share of step time, not as a bandwidth deficit.
    cat_rows = sorted(
        ({"category": c, "ms_per_step": ns / n_steps / 1e6,
          "pct": 100 * ns / total_ns, "ops_per_step": n // n_steps,
          "gbytes_per_step": round(b / 1e9, 3),
          "achieved_gbps": round(b * n_steps / ns, 1) if ns else 0.0}
         for c, (ns, n, b) in cats.items()),
        key=lambda r: -r["ms_per_step"])

    # MoE region rollup (router / dispatch / experts / combine / aux, fwd +
    # bwd): present only when the lowered module carries moe named-scope
    # tags — the per-category table behind PROFILE_MOE.md.
    moe_rows = None
    if len(moe_cats) > 1 or "non_moe" not in moe_cats:
        moe_rows = sorted(
            ({"region": c, "ms_per_step": round(ns / n_steps / 1e6, 3),
              "pct": round(100 * ns / total_ns, 2),
              "ops_per_step": n // n_steps,
              "gbytes_per_step": round(b / 1e9, 3),
              "achieved_gbps": round(b * n_steps / ns, 1) if ns else 0.0}
             for c, (ns, n, b) in moe_cats.items()),
            key=lambda r: -r["ms_per_step"])

    step_ms = total_ns / n_steps / 1e6
    flops = bundle.fwd_flops_per_example * 3 * per_chip_batch
    peak = metrics_lib.peak_flops_per_chip()
    module_ms = module_ns / max(module_runs, 1) / 1e6
    peak_bw = metrics_lib.peak_hbm_gbps()
    gbps = traffic_bytes / (module_ms / 1e3) / 1e9 if module_ms else 0.0
    roofline = {
        "hbm_bytes_per_step": round(traffic_bytes / 1e9, 3),
        "bytes_source": "measured_xplane_hlo_buffers",
        "measured_hbm_gbps": round(gbps, 1),
        "bw_fraction_of_peak": round(gbps / peak_bw, 3),
        "peak_hbm_gbps": peak_bw,
        "note": ("bytes = per-executed-op unique operand+result buffer "
                 "sizes from the scheduled HLO, joined to xplane events; "
                 "time = measured module duration"),
    }
    out = {
        "model": model_name,
        "device": jax.devices()[0].device_kind,
        "per_chip_batch": per_chip_batch,
        "precision": precision,
        "attn_impl": attn_impl,
        "steps_traced": n_steps,
        "xla_ops_ms_per_step": round(step_ms, 2),
        "module_ms_per_step": round(module_ms, 2),
        "mfu_from_op_time": round(flops / (step_ms / 1e3) / peak, 4),
        "unmatched_pct": round(100 * unmatched_ns / max(total_ns, 1), 2),
        "roofline_measured": roofline,
        "categories": [{**r, "ms_per_step": round(r["ms_per_step"], 2),
                        "pct": round(r["pct"], 1)} for r in cat_rows],
        **({"moe_regions": moe_rows,
            "moe_dispatch_impl": moe_dispatch_impl,
            "moe_top_k": moe_top_k,
            "moe_combine_dtype": moe_combine_dtype,
            "moe_capacity_factor": moe_capacity_factor,
            "moe_ep_dispatch": moe_ep_dispatch,
            "moe_ep_overlap_chunks": moe_ep_overlap_chunks}
           if moe_rows else {}),
        "top_ops": [{**r, "ms_per_step": round(r["ms_per_step"], 3)}
                    for r in rows[:top]],
        "trace_dir": trace_dir,
    }
    return out


def build_abstract_step(model_name: str, *, per_chip_batch=4,
                        precision="bf16", seq_len=2048, strategy=None,
                        remat=False, remat_policy="nothing",
                        attn_impl="auto", moe_capacity_factor=1.0,
                        moe_top_k=2, moe_dispatch_impl="gather",
                        moe_combine_dtype="fp32", moe_router_dtype="fp32",
                        moe_router_impl="reference",
                        moe_ep_dispatch="replicated",
                        moe_ep_overlap_chunks=2,
                        mesh_spec: dict | None = None,
                        pp_microbatches=4):
    """Chipless abstract train step: the shared lowering front-end.

    Builds the SAME program ``bench.setup_step`` times — same registry
    model, optimizer, strategy resolution — but with ABSTRACT inputs
    (``jax.eval_shape``; no params materialized), so callers can
    ``step.lower(abstract_state, abstract_batch)`` under ``mesh`` without a
    chip. Consumers: ``aot_report`` (per-region byte model, the
    ``--aot-bytes`` gate) and ``graftlint`` IR rules (donation / precision /
    host-transfer / sharding checks on the identical program).

    ``mesh_spec`` overrides the default data-only mesh (e.g.
    ``{"expert": 2, "data": -1}`` for the EP comms model); the lowering
    needs that many addressable devices — chipless CLI runs force fake CPU
    devices via XLA_FLAGS before jax initializes (see ``main``).

    Returns a dict with ``step`` (jitted, ``donate_argnums=0``),
    ``abstract_state``, ``abstract_batch``, ``mesh``, ``strategy``, and the
    resolved precision ``policy``.
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, precision as precision_lib, train_loop)
    from pytorch_distributed_training_example_tpu.core.train_state import (
        TrainState)
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.parallel import (
        sharding as sharding_lib)
    from pytorch_distributed_training_example_tpu.utils.config import (
        from_preset)

    mesh = mesh_lib.build_mesh(mesh_spec or {"data": -1})
    global_batch = per_chip_batch * mesh_lib.dp_size(mesh)
    cfg = from_preset("resnet50_imagenet", global_batch_size=global_batch,
                      precision=precision)
    strategy = strategy or ("fsdp" if "llama" in model_name
                            or "gpt" in model_name else cfg.strategy)
    policy = precision_lib.get_policy(cfg.precision)
    bundle = registry.create_model(model_name, seq_len=seq_len,
                                   dtype=policy.compute_dtype,
                                   param_dtype=policy.param_dtype,
                                   remat=remat, remat_policy=remat_policy,
                                   attn_impl=attn_impl,
                                   moe_capacity_factor=moe_capacity_factor,
                                   moe_top_k=moe_top_k,
                                   moe_dispatch_impl=moe_dispatch_impl,
                                   moe_combine_dtype=moe_combine_dtype,
                                   moe_router_dtype=moe_router_dtype,
                                   moe_router_impl=moe_router_impl,
                                   moe_ep_dispatch=moe_ep_dispatch,
                                   moe_ep_overlap_chunks=moe_ep_overlap_chunks,
                                   logits_dtype=policy.logits_dtype)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=1000)
    if strategy == "pp":
        # Pipeline rows reuse the trainer's wiring: scan-stacked Llama
        # blocks sharded over 'stage', GPipe microbatch schedule
        # (parallel/pp_lm.py). The wrapper quacks like a flax module, so
        # the abstract lowering below is unchanged.
        from pytorch_distributed_training_example_tpu.parallel import pp_lm

        module = pp_lm.PipelinedLlama(bundle.module, mesh,
                                      num_microbatches=pp_microbatches)
        rules = pp_lm.PP_RULES
    else:
        rules = sharding_lib.strategy_rules(strategy, bundle.rules)
        module = bundle.module

    def init_fn(rng):
        variables = module.init({"params": rng}, *jax.tree.map(
            lambda t: t[:1], bundle.input_template), train=False)
        return TrainState.create(apply_fn=module.apply,
                                 params=variables["params"], tx=tx,
                                 rng=jax.random.PRNGKey(0))

    state_shape = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = train_loop.state_shardings(state_shape, mesh, rules)
    abstract_state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_shape, shardings)
    batch_sh = mesh_lib.batch_sharding(mesh)
    abstract_batch = {
        "tokens": jax.ShapeDtypeStruct((global_batch, seq_len), jnp.int32,
                                       sharding=batch_sh),
        "targets": jax.ShapeDtypeStruct((global_batch, seq_len), jnp.int32,
                                        sharding=batch_sh),
    }
    step = jax.jit(train_loop.make_train_step(
        train_loop.get_task(bundle.task)), donate_argnums=0)
    return {
        "step": step,
        "abstract_state": abstract_state,
        "abstract_batch": abstract_batch,
        "mesh": mesh,
        "strategy": strategy,
        "policy": policy,
    }


def aot_report(model_name: str, *, per_chip_batch=4, precision="bf16",
               seq_len=2048, strategy=None, remat=False,
               remat_policy="nothing", attn_impl="auto",
               moe_capacity_factor=1.0, moe_top_k=2,
               moe_dispatch_impl="gather", moe_combine_dtype="fp32",
               moe_router_dtype="fp32", moe_router_impl="reference",
               moe_ep_dispatch="replicated", moe_ep_overlap_chunks=2,
               ep_degree=1, seq_degree=1, pp_degree=1, dp_degree=0,
               pp_microbatches=4):
    """Chipless per-region program report (the derived leg of PROFILE_MOE.md).

    AOT-lowers the SAME train step bench.py times — same registry model,
    optimizer, strategy resolution as ``bench.setup_step`` — but with
    ABSTRACT inputs (``jax.eval_shape``; no params materialized), then
    classifies every instruction of the compiled module by its moe
    named-scope tag and tabulates static program facts per region: op
    counts, modeled HBM bytes (``build_op_bytes``), and the HLO category
    mix. No timing. The fusion/schedule is THIS process' XLA backend (on a
    CPU host: XLA:CPU) — op counts and logical bytes are facts of the
    lowered program, but TPU fusion differs, so downstream consumers must
    label these numbers derived, not measured.

    Region BYTES use proportional attribution (``build_op_moe_weights``):
    a mixed fusion's traffic is split across regions by interior-line
    result bytes instead of winner-take-all line majority, which on
    XLA:CPU charged whole-block backward mega-fusions to whichever MoE
    region tagged a few cotangent lines (see the r8 PROFILE_MOE.md
    addendum). Integer op counts and the category mix still use the
    majority map — an instruction is one op in one region. The output
    carries ``"attribution": "proportional_bytes"`` so byte goldens
    recorded under one model never compare against the other.

    Pallas-kernel interior ops from the off-TPU interpret lowering are
    excluded wholesale (``build_pallas_interior``): the grid while-loop
    that emulates the kernel on CPU is not part of the target program,
    and the kernel's real HBM charge — operands + results, as for any
    custom call — is carried by the while instruction's boundary tuple.

    ``ep_degree > 1`` lowers at an ``{"expert": ep, "data": rest}`` mesh
    (strategy defaults to the model's ``fsdp_tp`` table — the one that
    pins ``moe/experts/w_*`` to the expert axis) and the ``collectives``
    census becomes the EP comms model: per-opcode/per-region bytes that
    the a2a-vs-replicated golden rows gate (``check_regression.py
    --aot-bytes``).

    ``seq_degree`` / ``pp_degree`` / ``dp_degree`` compose the full
    topology tuple (dp x ep x pp x seq): the mesh gains a ``context`` /
    ``stage`` axis and the report becomes the per-topology memory+comms
    census — ring-attention ppermute bytes land in the collectives
    census, and ``memory`` carries the abstract lowering's HBM high-water
    (``compiled.memory_analysis()``: resident = arguments + temps under
    donation). ``pp_degree > 1`` forces strategy "pp" (the GPipe schedule
    over scan-stacked Llama blocks). ``dp_degree == 0`` lets the data
    axis absorb the remaining devices (the historical single-axis
    behavior); setting it pins the data axis so one report is one
    (dp, ep, pp, seq) tuple."""
    mesh_spec = None
    if ep_degree > 1 or seq_degree > 1 or pp_degree > 1 or dp_degree:
        mesh_spec = {a: d for a, d in (("expert", ep_degree),
                                       ("context", seq_degree),
                                       ("stage", pp_degree)) if d > 1}
        mesh_spec["data"] = dp_degree if dp_degree else -1
        if pp_degree > 1:
            strategy = "pp"
        elif ep_degree > 1:
            strategy = strategy or "fsdp_tp"
    built = build_abstract_step(
        model_name, per_chip_batch=per_chip_batch, precision=precision,
        seq_len=seq_len, strategy=strategy, remat=remat,
        remat_policy=remat_policy, attn_impl=attn_impl,
        moe_capacity_factor=moe_capacity_factor, moe_top_k=moe_top_k,
        moe_dispatch_impl=moe_dispatch_impl,
        moe_combine_dtype=moe_combine_dtype,
        moe_router_dtype=moe_router_dtype,
        moe_router_impl=moe_router_impl,
        moe_ep_dispatch=moe_ep_dispatch,
        moe_ep_overlap_chunks=moe_ep_overlap_chunks,
        mesh_spec=mesh_spec, pp_microbatches=pp_microbatches)
    import jax

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib)

    strategy = built["strategy"]
    with mesh_lib.use_mesh(built["mesh"]):
        compiled = built["step"].lower(
            built["abstract_state"], built["abstract_batch"]).compile()
    hlo_text = compiled.as_text()
    op_cat, _ = build_op_categories(hlo_text)
    op_bytes = build_op_bytes(hlo_text)
    op_moe = build_op_moe_tags(hlo_text)
    op_w = build_op_moe_weights(hlo_text)
    # Off-TPU lowering emulates Pallas kernels as grid while-loops; their
    # interior ops are not target-program ops and would charge phantom
    # full-array traffic per grid step (see _PALLAS_INTERIOR_RE).
    op_interior = build_pallas_interior(hlo_text)

    regions: dict[str, dict] = {}

    def row(tag):
        return regions.setdefault(tag, {"ops": 0, "gbytes_modeled": 0.0,
                                        "by_category": collections.Counter()})

    for op, b in op_bytes.items():
        if op in op_interior:
            continue
        assigned = 0.0
        for tag, frac in op_w.get(op, {}).items():
            row(tag)["gbytes_modeled"] += b * frac / 1e9
            assigned += frac
        if assigned < 1.0:
            row("non_moe")["gbytes_modeled"] += b * (1.0 - assigned) / 1e9
        r = row(op_moe.get(op, "non_moe"))
        r["ops"] += 1
        if b or op_cat.get(op) not in (None, "copy_layout"):
            r["by_category"][op_cat.get(op, "?")] += 1
    for row in regions.values():
        row["gbytes_modeled"] = round(row["gbytes_modeled"], 3)
        row["by_category"] = dict(row["by_category"].most_common(6))
    try:
        ca = compiled.cost_analysis() or {}
    except Exception:
        ca = {}
    if isinstance(ca, list):  # older jax: one dict per program
        ca = ca[0] if ca else {}
    # Per-device HBM high-water of the abstract lowering. Under donation the
    # resident set is arguments + temps (outputs alias donated inputs), which
    # is what the v5p 95 GB budget gates in FEASIBILITY_8B.json. This is the
    # host backend's buffer assignment — CPU temps run ~2x the TPU assignment
    # at 8B scale (no fusion of the attention softmax), so consumers compare
    # rows against rows, never against the raw chip budget.
    memory = None
    try:
        ma = compiled.memory_analysis()
        memory = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "resident_bytes": int(ma.argument_size_in_bytes
                                  + ma.temp_size_in_bytes),
        }
    except Exception:
        pass
    return {
        "mode": "aot_hlo_model",
        "attribution": "proportional_bytes",
        "backend_lowering": jax.default_backend(),
        "model": model_name,
        "per_chip_batch": per_chip_batch,
        "seq_len": seq_len,
        "precision": precision,
        "strategy": strategy,
        "moe_dispatch_impl": moe_dispatch_impl,
        "moe_top_k": moe_top_k,
        "moe_combine_dtype": moe_combine_dtype,
        "moe_router_dtype": moe_router_dtype,
        "moe_router_impl": moe_router_impl,
        "moe_capacity_factor": moe_capacity_factor,
        "moe_ep_dispatch": moe_ep_dispatch,
        "moe_ep_overlap_chunks": moe_ep_overlap_chunks,
        "ep_degree": ep_degree,
        "seq_degree": seq_degree,
        "pp_degree": pp_degree,
        "dp_degree": dp_degree,
        "attn_impl": attn_impl,
        "xla_flops_per_step": ca.get("flops"),
        "xla_bytes_accessed": ca.get("bytes accessed"),
        "memory": memory,
        "collectives": collective_byte_census(hlo_text),
        "regions": dict(sorted(regions.items(),
                               key=lambda kv: -kv[1]["gbytes_modeled"])),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="vit_b16")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--per-chip-batch", type=int, default=64)
    p.add_argument("--precision", default="bf16")
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--strategy", default=None)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat-policy", default="nothing",
                   choices=["nothing", "dots", "dots_no_batch", "attn_out"])
    p.add_argument("--attn-impl", default="auto")
    p.add_argument("--moe-top-k", type=int, default=2)
    p.add_argument("--moe-dispatch", default="gather",
                   choices=["sort", "gather", "einsum", "dropless"])
    p.add_argument("--moe-combine", default="fp32", choices=["fp32", "bf16"])
    p.add_argument("--moe-router-dtype", default="fp32",
                   choices=["fp32", "bf16"])
    p.add_argument("--moe-router-impl", default="reference",
                   choices=["reference", "fused"])
    p.add_argument("--moe-capacity-factor", type=float, default=1.25)
    p.add_argument("--moe-ep-dispatch", default="replicated",
                   choices=["replicated", "a2a", "a2a_overlap"],
                   dest="moe_ep_dispatch",
                   help="dropless EP transport (parallel/moe.py); with "
                        "--aot --ep N the collectives census becomes the "
                        "chipless EP comms model")
    p.add_argument("--moe-ep-overlap-chunks", type=int, default=2,
                   dest="moe_ep_overlap_chunks",
                   help="a2a_overlap double-buffer windows over the token "
                        "dim (chunk count reaches the lowered program)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree for --aot: lower at an "
                        "{expert: N, data: rest} mesh (forces N fake CPU "
                        "host devices when run chipless)")
    p.add_argument("--seq-par", type=int, default=1, dest="seq_par",
                   help="sequence/context-parallel degree for --aot: the "
                        "mesh gains a context axis; pair with "
                        "--attn-impl ring for the sharded-KV lowering")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel degree for --aot: wraps the "
                        "model in the GPipe schedule over a stage axis "
                        "(llama family, layers %% stages == 0)")
    p.add_argument("--dp", type=int, default=0,
                   help="pin the data axis for --aot (0 = absorb the "
                        "remaining devices); with --ep/--pp/--seq-par one "
                        "report is one (dp, ep, pp, seq) topology tuple")
    p.add_argument("--pp-microbatches", type=int, default=4,
                   dest="pp_microbatches",
                   help="GPipe microbatch count when --pp > 1")
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--telemetry", action="store_true",
                   help="profile the step WITH the on-device health pack "
                        "compiled in (utils/telemetry.py) — its reductions "
                        "show up under the telemetry_health named scope")
    p.add_argument("--aot", action="store_true",
                   help="no-chip mode: AOT-lower with abstract inputs and "
                        "report static per-moe-region program facts "
                        "(modeled bytes/op counts) instead of traced times")
    p.add_argument("--out", default=None, help="write full JSON here")
    args = p.parse_args(argv)
    if args.aot:
        ndev = max(args.dp, 1) * args.ep * args.seq_par * args.pp
        if ndev > 1 and "jax" not in sys.modules:
            # Chipless composed-mesh lowering needs dp*ep*pp*seq addressable
            # devices; must land before the first jax import in this process.
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count={ndev}")
        res = aot_report(args.model, per_chip_batch=args.per_chip_batch,
                         precision=args.precision, seq_len=args.seq_len,
                         strategy=args.strategy, remat=args.remat,
                         remat_policy=args.remat_policy,
                         attn_impl=args.attn_impl,
                         moe_capacity_factor=args.moe_capacity_factor,
                         moe_top_k=args.moe_top_k,
                         moe_dispatch_impl=args.moe_dispatch,
                         moe_combine_dtype=args.moe_combine,
                         moe_router_dtype=args.moe_router_dtype,
                         moe_router_impl=args.moe_router_impl,
                         moe_ep_dispatch=args.moe_ep_dispatch,
                         moe_ep_overlap_chunks=args.moe_ep_overlap_chunks,
                         ep_degree=args.ep, seq_degree=args.seq_par,
                         pp_degree=args.pp, dp_degree=args.dp,
                         pp_microbatches=args.pp_microbatches)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(res, f, indent=1)
        print(json.dumps(res))
        return 0
    res = profile(args.model, image_size=args.image_size,
                  per_chip_batch=args.per_chip_batch, precision=args.precision,
                  seq_len=args.seq_len, strategy=args.strategy,
                  remat=args.remat, remat_policy=args.remat_policy,
                  attn_impl=args.attn_impl,
                  moe_capacity_factor=args.moe_capacity_factor,
                  moe_top_k=args.moe_top_k,
                  moe_dispatch_impl=args.moe_dispatch,
                  moe_combine_dtype=args.moe_combine,
                  moe_router_dtype=args.moe_router_dtype,
                  moe_router_impl=args.moe_router_impl,
                  moe_ep_dispatch=args.moe_ep_dispatch,
                  moe_ep_overlap_chunks=args.moe_ep_overlap_chunks,
                  steps=args.steps, top=args.top, telemetry=args.telemetry)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(res, f, indent=1)
    slim = {k: res[k] for k in ("model", "device", "xla_ops_ms_per_step",
                                "module_ms_per_step", "mfu_from_op_time",
                                "unmatched_pct")}
    slim["roofline_measured"] = res["roofline_measured"]
    for c in res["categories"]:
        print(json.dumps(c), file=sys.stderr)
    for c in res.get("moe_regions") or []:
        print(json.dumps(c), file=sys.stderr)
    print(json.dumps(slim))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
