#!/usr/bin/env python
"""Microbenchmark: fused BN-apply/ReLU->matmul->BN-stats Pallas kernel vs
XLA's unfused schedule, on the real chip (VERDICT r2 #1).

Measures the ResNet-50 bottleneck 1x1-conv segment as a matmul:

    unfused (what XLA runs today):  xn = relu(x*scale+bias)   (elementwise pass)
                                    y  = xn @ w               (conv)
                                    s  = sum(y,0), ss = sum(y^2,0)  (stats pass)
    fused (ops/fused_bn_matmul.py): one pass, stats from the VMEM-resident y.

Timing is SLOPE-BASED: the remote attachment adds a large fixed dispatch
cost per executable call (~75 ms measured — see BENCH_FLASH_MICRO.json),
so each arm is compiled as a chained ``lax.scan`` at two trip counts and
the per-iteration time is (t_long - t_short) / (iters_long - iters_short),
which cancels the fixed cost exactly. Iterations are chained through a
scalar so nothing is dead-code-eliminated or overlapped.

    python benchmarks/fused_bn_bench.py [--out BENCH_FUSED_BN.json]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ITERS_SHORT = 20
ITERS_LONG = 120

# [B*H*W, Cin, Cout] instances of the bottleneck 1x1 convs at batch 128
# (stage2 reduce/expand, stage3 reduce), PROFILE_RN50.md's canonical shapes.
SHAPES = [
    (128 * 56 * 56, 256, 64),    # stage2 reduce: 206 MB activation
    (128 * 56 * 56, 64, 256),    # stage2 expand
    (128 * 28 * 28, 512, 128),   # stage3 reduce
]


def _timed_at(fn, *args):
    """Compile fn(*args), return best wall seconds over 3 synced runs."""
    import jax
    import numpy as np

    compiled = jax.jit(fn).lower(*args).compile()
    out = compiled(*args)
    np.asarray(jax.tree.leaves(out)[0])  # force
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = compiled(*args)
        np.asarray(jax.tree.leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best


def _timed_pair(make_un, make_fu, *args, reps=3):
    """Interleaved A/B slope timing: [unfused, fused] per-iter seconds.

    Tunnel load drifts on the scale of a single measurement, so the two
    arms are measured back-to-back in each repetition (A,B,A,B,...) and the
    per-arm slope uses the min over repetitions at each trip count —
    uncorrelated drift then inflates both arms equally instead of flipping
    the ratio between runs.
    """
    loops = {}
    for tag, mk in (("un", make_un), ("fu", make_fu)):
        for L in (ITERS_SHORT, ITERS_LONG):
            loops[tag, L] = mk(L)
    best = {k: float("inf") for k in loops}
    times = {("un", ITERS_SHORT): [], ("un", ITERS_LONG): [],
             ("fu", ITERS_SHORT): [], ("fu", ITERS_LONG): []}
    for _ in range(reps):
        for key, fn in loops.items():
            t = _timed_at(fn, *args)
            best[key] = min(best[key], t)
            times[key].append(round(t * 1e3, 1))
    out = []
    for tag in ("un", "fu"):
        slope = max(best[tag, ITERS_LONG] - best[tag, ITERS_SHORT], 1e-9)
        out.append(slope / (ITERS_LONG - ITERS_SHORT))
    return out[0], out[1], {k[0] + str(k[1]): v for k, v in times.items()}


def bench_shape(N, K, C, dtype_name="bfloat16"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_example_tpu.ops import fused_bn_matmul as fbm

    dtype = jnp.dtype(dtype_name)
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(N, K), dtype)
    w = jnp.asarray(r.randn(K, C) / np.sqrt(K), dtype)
    scale = jnp.asarray(1 + 0.1 * r.randn(1, K), dtype)
    bias = jnp.asarray(0.1 * r.randn(1, K), dtype)
    Cp = max(128, -(-C // 128) * 128)
    wp = jnp.pad(w, ((0, 0), (0, Cp - C))) if Cp != C else w

    def unfused_once(x, carry):
        xn = jnp.maximum(x * scale + bias, 0.0)
        y = jnp.dot(xn, w, preferred_element_type=jnp.float32).astype(dtype)
        yf = y.astype(jnp.float32)
        s, ss = jnp.sum(yf, 0), jnp.sum(yf * yf, 0)
        return y, s, ss

    def fused_once(x, carry):
        y, stats = fbm.fused_stats_matmul(x, wp, scale, bias, relu=True)
        return y, stats[0], stats[1]

    def loop(once):
        def make(iters):
            def body(carry, _):
                # Chain: perturb x by a scalar of the previous stats so each
                # iteration depends on the last (no overlap/DCE), ~1 vadd.
                xi = x + (carry * 1e-30).astype(dtype)
                y, s, ss = once(xi, carry)
                return s[0] + ss[0], y[0, 0]

            def run(x0):
                c, ys = jax.lax.scan(body, x0, None, length=iters)
                return c, ys

            return run

        return make

    t_un, t_fu, raw = _timed_pair(loop(unfused_once), loop(fused_once),
                                  jnp.float32(0))

    bpe = jnp.finfo(dtype).bits // 8
    # Logical HBM traffic per iteration (reads of x + write/read of y):
    unfused_bytes = (N * K * bpe) * 2 + (N * K * bpe) + 2 * (N * C * bpe)
    fused_bytes = N * K * bpe + N * C * bpe
    return {
        "shape": {"N": N, "K": K, "C": C, "dtype": dtype_name},
        "unfused_ms": round(t_un * 1e3, 3),
        "fused_ms": round(t_fu * 1e3, 3),
        "speedup": round(t_un / t_fu, 3),
        "raw_wall_ms": raw,
        "unfused_logical_gb": round(unfused_bytes / 1e9, 3),
        "fused_logical_gb": round(fused_bytes / 1e9, 3),
        "unfused_gbps": round(unfused_bytes / t_un / 1e9, 1),
        "fused_gbps": round(fused_bytes / t_fu / 1e9, 1),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="BENCH_FUSED_BN.json")
    args = p.parse_args(argv)
    import jax

    rows = [bench_shape(*s) for s in SHAPES]
    out = {
        "bench": "fused_bn_matmul_vs_xla",
        "device": jax.devices()[0].device_kind,
        "iters": [ITERS_SHORT, ITERS_LONG],
        "timing": "two-trip-count slope (cancels fixed dispatch cost), "
                  "chained scan, best of 3 per point",
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"rows": [{**r["shape"], "speedup": r["speedup"]}
                               for r in rows], "out": args.out}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
