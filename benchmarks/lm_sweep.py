#!/usr/bin/env python
"""GPT-2 MFU sweep (VERDICT r2 #2): batch x remat x attn-impl x flash blocks.

Runs the same compiled-scan train-step harness as bench.py over a grid of
configs on the real chip and records every row (including OOM failures) to
LM_SWEEP.json. The best row is the candidate for bench.py's LM headline and
benchmarks/golden.json.

Each row runs in a FRESH SUBPROCESS: a row that OOMs (or wedges the remote
compile helper) leaves the process unable to allocate for every later row —
the first in-process sweep recorded spurious OOMs for configs that fit
comfortably when run alone. The persistent compile cache keeps the per-row
re-init cost to seconds.

Usage:
    python benchmarks/lm_sweep.py [--out LM_SWEEP.json] [--quick]
    python benchmarks/lm_sweep.py --row '<json>'   # internal: one point
"""

from __future__ import annotations

import argparse
import functools
import json
import subprocess
import sys
import time


def run_row_inprocess(bench_mod, flash_mod, *, batch, seq_len, remat,
                      attn_impl, block_q=None, block_kv=None, impl=None,
                      steps=10, warmup=4):
    """One sweep point; returns the bench row dict or an error record."""
    label = {"per_chip_batch": batch, "seq_len": seq_len, "remat": remat,
             "attn_impl": attn_impl,
             "block_q": block_q or flash_mod.DEFAULT_BLOCK_Q,
             "block_kv": block_kv or flash_mod.DEFAULT_BLOCK_KV,
             "impl": impl or "auto"}
    orig = flash_mod.flash_attention
    try:
        if block_q or block_kv or impl:
            # attention() calls flash_attention() with default blocks; pin
            # the sweep's blocks without plumbing a new argument everywhere.
            # Block sizes only reach the ONLINE kernels — "auto" dispatches
            # these shapes to the one-shot kernel, which ignores them — so
            # block rows must pin impl="online" to measure anything.
            wrapped = functools.partial(
                orig, block_q=block_q or flash_mod.DEFAULT_BLOCK_Q,
                block_kv=block_kv or flash_mod.DEFAULT_BLOCK_KV,
                impl=impl or "auto")
            flash_mod.flash_attention = wrapped
        t0 = time.perf_counter()
        row = bench_mod.bench("gpt2", per_chip_batch=batch, steps=steps,
                              warmup=warmup, precision="bf16",
                              seq_len=seq_len, remat=remat,
                              attn_impl=attn_impl, quiet=True)
        label.update(mfu=row["extra"]["mfu"], step_ms=row["extra"]["step_ms"],
                     seq_per_sec_chip=row["value"],
                     wall_s=round(time.perf_counter() - t0, 1), ok=True)
    except Exception as e:  # OOM rows are data, not crashes
        msg = str(e)
        label.update(ok=False,
                     error=("OOM" if "RESOURCE_EXHAUSTED" in msg
                            or "Out of memory" in msg else msg[:200]))
    finally:
        flash_mod.flash_attention = orig
    return label


def run_row(**point):
    """Run one sweep point in a fresh subprocess (isolated allocator)."""
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--row", json.dumps(point)],
            capture_output=True, text=True, timeout=900, cwd=".",
        )
    except subprocess.TimeoutExpired:
        # A wedged row (hung compile helper) is data too; keep sweeping.
        label = dict(point, ok=False, error="subprocess timeout (900s)")
        print(json.dumps(label), file=sys.stderr, flush=True)
        return label
    out = proc.stdout.strip().splitlines()
    try:
        label = json.loads(out[-1])
    except (IndexError, json.JSONDecodeError):
        label = dict(point, ok=False,
                     error=f"subprocess rc={proc.returncode}: "
                           f"{proc.stderr.strip()[-200:]}")
    print(json.dumps(label), file=sys.stderr, flush=True)
    return label


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="LM_SWEEP.json")
    p.add_argument("--quick", action="store_true",
                   help="batch/remat grid only (skip block + S=2048 axes)")
    p.add_argument("--row", default=None,
                   help="internal: run one json-encoded point in-process")
    args = p.parse_args(argv)

    if args.row:
        import bench as bench_mod
        from pytorch_distributed_training_example_tpu.ops import (
            flash_attention as flash_mod)

        label = run_row_inprocess(bench_mod, flash_mod,
                                  **json.loads(args.row))
        print(json.dumps(label))
        return 0

    import jax

    rows = []
    # Axis 1: per-chip batch x remat at S=1024, flash attention.
    for batch in (8, 16, 32, 64):
        for remat in (False, True):
            rows.append(run_row(batch=batch, seq_len=1024, remat=remat,
                                attn_impl="flash"))
    # Axis 2: XLA attention at the best-looking batches (flash vs XLA).
    for batch in (16, 32):
        rows.append(run_row(batch=batch, seq_len=1024, remat=False,
                            attn_impl="xla"))
    if not args.quick:
        # Axis 3: ONLINE-kernel block sizes at the best batch (the one-shot
        # kernel self-plans its tiling, so blocks only exist on the online
        # path), plus one forced-online row at default blocks as the
        # oneshot-vs-online e2e comparison.
        for bq, bkv in ((512, 512), (256, 512), (1024, 512), (512, 1024),
                        (1024, 1024)):
            rows.append(run_row(batch=16, seq_len=1024, remat=False,
                                attn_impl="flash", block_q=bq, block_kv=bkv,
                                impl="online"))
        # Axis 4: S=2048 (longer sequence shifts attention share upward).
        for batch in (4, 8, 16):
            rows.append(run_row(batch=batch, seq_len=2048, remat=False,
                                attn_impl="flash"))

    ok_rows = [r for r in rows if r.get("ok")]
    best = max(ok_rows, key=lambda r: r["mfu"]) if ok_rows else None
    out = {
        "sweep": "gpt2_mfu",
        "device": jax.devices()[0].device_kind,
        "target_mfu": 0.55,
        "best": best,
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"best": best, "n_rows": len(rows),
                      "out": args.out}))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
