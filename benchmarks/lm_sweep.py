#!/usr/bin/env python
"""GPT-2 MFU sweep (VERDICT r2 #2): batch x remat x attn-impl x flash blocks.

Runs the same compiled-scan train-step harness as bench.py over a grid of
configs on the real chip and records every row (including OOM failures) to
LM_SWEEP.json. The best row is the candidate for bench.py's LM headline and
benchmarks/golden.json.

Usage:
    python benchmarks/lm_sweep.py [--out LM_SWEEP.json] [--quick]
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time


def run_row(bench_mod, flash_mod, *, batch, seq_len, remat, attn_impl,
            block_q=None, block_kv=None, steps=10, warmup=4):
    """One sweep point; returns the bench row dict or an error record."""
    label = {"per_chip_batch": batch, "seq_len": seq_len, "remat": remat,
             "attn_impl": attn_impl,
             "block_q": block_q or flash_mod.DEFAULT_BLOCK_Q,
             "block_kv": block_kv or flash_mod.DEFAULT_BLOCK_KV}
    orig = flash_mod.flash_attention
    try:
        if block_q or block_kv:
            # attention() calls flash_attention() with default blocks; pin
            # the sweep's blocks without plumbing a new argument everywhere.
            wrapped = functools.partial(
                orig, block_q=block_q or flash_mod.DEFAULT_BLOCK_Q,
                block_kv=block_kv or flash_mod.DEFAULT_BLOCK_KV)
            flash_mod.flash_attention = wrapped
        t0 = time.perf_counter()
        row = bench_mod.bench("gpt2", per_chip_batch=batch, steps=steps,
                              warmup=warmup, precision="bf16",
                              seq_len=seq_len, remat=remat,
                              attn_impl=attn_impl, quiet=True)
        label.update(mfu=row["extra"]["mfu"], step_ms=row["extra"]["step_ms"],
                     seq_per_sec_chip=row["value"],
                     wall_s=round(time.perf_counter() - t0, 1), ok=True)
    except Exception as e:  # OOM rows are data, not crashes
        msg = str(e)
        label.update(ok=False,
                     error=("OOM" if "RESOURCE_EXHAUSTED" in msg
                            or "Out of memory" in msg else msg[:200]))
    finally:
        flash_mod.flash_attention = orig
    print(json.dumps(label), file=sys.stderr, flush=True)
    return label


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="LM_SWEEP.json")
    p.add_argument("--quick", action="store_true",
                   help="batch/remat grid only (skip block + S=2048 axes)")
    args = p.parse_args(argv)

    import jax

    import bench as bench_mod
    from pytorch_distributed_training_example_tpu.ops import (
        flash_attention as flash_mod)

    rows = []
    # Axis 1: per-chip batch x remat at S=1024, flash attention.
    for batch in (8, 16, 32, 64):
        for remat in (False, True):
            rows.append(run_row(bench_mod, flash_mod, batch=batch,
                                seq_len=1024, remat=remat, attn_impl="flash"))
    # Axis 2: XLA attention at the best-looking batches (flash vs XLA).
    for batch in (16, 32):
        rows.append(run_row(bench_mod, flash_mod, batch=batch, seq_len=1024,
                            remat=False, attn_impl="xla"))
    if not args.quick:
        # Axis 3: flash block sizes at the best batch (S=1024 -> blocks
        # divide 1024; 512 is the default).
        for bq, bkv in ((256, 256), (256, 512), (512, 256), (1024, 512),
                        (512, 1024), (1024, 1024)):
            rows.append(run_row(bench_mod, flash_mod, batch=32, seq_len=1024,
                                remat=False, attn_impl="flash",
                                block_q=bq, block_kv=bkv))
        # Axis 4: S=2048 (longer sequence shifts attention share upward).
        for batch in (4, 8, 16):
            rows.append(run_row(bench_mod, flash_mod, batch=batch,
                                seq_len=2048, remat=False, attn_impl="flash"))

    ok_rows = [r for r in rows if r.get("ok")]
    best = max(ok_rows, key=lambda r: r["mfu"]) if ok_rows else None
    out = {
        "sweep": "gpt2_mfu",
        "device": jax.devices()[0].device_kind,
        "target_mfu": 0.55,
        "best": best,
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"best": best, "n_rows": len(rows),
                      "out": args.out}))
    return 0


if __name__ == "__main__":
    sys.path.insert(0, ".")
    raise SystemExit(main())
