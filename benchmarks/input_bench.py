#!/usr/bin/env python
"""Input-pipeline throughput artifact (VERDICT r2 #5; SURVEY.md §7(a)).

Measures the loader alone (JPEG decode + augment + collate, no device) at a
worker-count sweep, for both the native C++ engine and the Python/PIL path,
then answers the feed-rate question: how many host cores does it take to
feed the measured ResNet-50 device rate (golden.json)?

This CI host has very few cores (os.cpu_count() is recorded in the
artifact); the per-core rate is computed at the worker count that maximizes
throughput, and the cores-needed figure extrapolates linearly — the loader
is embarrassingly parallel across images (per-sample RNG is keyed on
dataset index, so parallelism does not change results).

    python benchmarks/input_bench.py [--out BENCH_INPUT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="BENCH_INPUT.json")
    p.add_argument("--data-path", default=None)
    p.add_argument("--batch-size", type=int, default=128)
    # Must comfortably exceed the loader's prefetch budget
    # (max(prefetch_batches, workers) = 8 at the sweep's top) or the timed
    # loop drains already-decoded buffers and reads absurdly high.
    p.add_argument("--batches", type=int, default=24)
    p.add_argument("--workers", default="1,2,4,8")
    args = p.parse_args(argv)

    from bench import bench_input

    golden_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "golden.json")
    with open(golden_path) as f:
        golden = json.load(f)
    device_rate = (golden.get("TPU v5 lite", {})
                   .get("resnet50_imagenet_train_throughput", {})
                   .get("value"))

    rows = []
    for native in (True, False):
        for w in [int(x) for x in args.workers.split(",")]:
            try:
                r = bench_input(args.data_path, batch_size=args.batch_size,
                                batches=args.batches, workers=w,
                                native=native)
                rows.append({"workers": w, "native_requested": native, **r})
            except Exception as e:
                rows.append({"workers": w, "native_requested": native,
                             "ok": False, "error": str(e)[:200]})
            print(json.dumps(rows[-1]), file=sys.stderr, flush=True)

    # Feed-rate answer from the NATIVE path (the production loader);
    # python-loader rows are recorded for comparison only.
    ok = [r for r in rows if r.get("input_images_per_sec")
          and r.get("input_loader") == "native_jpeg"]
    best = max(ok, key=lambda r: r["input_images_per_sec"]) if ok else None
    cores = os.cpu_count() or 1
    summary = {}
    if best and device_rate:
        # Per-core rate uses the parallelism actually exercised: when peak
        # throughput lands at fewer workers than cores, dividing by
        # os.cpu_count() understates it (r3 advisor); when workers
        # oversubscribe cores, the core count is the true divisor.
        used = max(1, min(best["workers"], cores))
        per_core = best["input_images_per_sec"] / used
        summary = {
            "loader": "native_jpeg",
            "best_images_per_sec": best["input_images_per_sec"],
            "best_workers": best["workers"],
            "host_cpus": cores,
            "cores_used_at_best": used,
            "images_per_sec_per_core": round(per_core, 1),
            "device_rate_images_per_sec_per_chip": device_rate,
            "cores_to_feed_one_chip": round(device_rate / per_core, 1),
        }
    out = {
        "bench": "input_pipeline",
        "note": "loader-only host throughput; device untouched. Extrapolated "
                "linearly from this host's core count (decode is "
                "embarrassingly parallel across images).",
        "rows": rows,
        "summary": summary,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"summary": summary, "out": args.out}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
