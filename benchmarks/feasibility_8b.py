#!/usr/bin/env python
"""Llama-3 8B feasibility proof (VERDICT r2 #4; BASELINE.json configs[4]).

AOT-lowers and compiles the FULL fsdp+remat train step for ``llama3_8b`` on a
virtual CPU mesh (16 and 32 devices) with ABSTRACT inputs — no 32 GB of
parameters is ever materialized — and records the compiled executable's own
``memory_analysis()`` per-device byte counts against the v5p HBM budget
(95 GB/chip). This is the scaled-up version of the pattern
``tests/test_transformers.py::test_sp_reduces_activation_memory`` uses.

Caveat recorded in the artifact: the executable is compiled by the CPU
backend, so temp-buffer sizes reflect XLA:CPU's buffer assignment, not
XLA:TPU's (which fuses more aggressively); argument/output sizes (params,
optimizer state, batch) are backend-independent sharded-shape facts.

    python benchmarks/feasibility_8b.py [--out FEASIBILITY_8B.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5P_HBM_BYTES = 95e9
MAX_DEVICES = 32


def analyze(n_devices: int, seq_len: int, per_device_batch: int = 1):
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, train_loop)
    from pytorch_distributed_training_example_tpu.core.train_state import TrainState
    from pytorch_distributed_training_example_tpu.models import llama as llama_lib
    from pytorch_distributed_training_example_tpu.parallel import (
        sharding as sharding_lib)
    from pytorch_distributed_training_example_tpu.utils.config import Config

    devices = jax.devices("cpu")[:n_devices]
    mesh = mesh_lib.build_mesh({"fsdp": n_devices}, devices=devices)
    module = llama_lib.llama3_8b(dtype=jnp.bfloat16, param_dtype=jnp.float32,
                                 remat=True, scan_layers=True,
                                 max_seq_len=seq_len)
    n_params = llama_lib.num_params(module)
    tx, _ = optim.build_optimizer(
        Config(lr=3e-4, optimizer="adamw", weight_decay=0.1),
        steps_per_epoch=1000)
    rules = sharding_lib.strategy_rules("fsdp", llama_lib.TP_RULES)

    B = per_device_batch * n_devices
    tokens = jax.ShapeDtypeStruct((B, seq_len), jnp.int32)

    def init_fn(rng):
        variables = module.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                                train=False)
        return TrainState.create(apply_fn=module.apply,
                                 params=variables["params"], tx=tx,
                                 rng=jax.random.PRNGKey(0))

    t0 = time.perf_counter()
    state_shape = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = train_loop.state_shardings(state_shape, mesh, rules)
    abstract_state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_shape, shardings)
    batch_sh = mesh_lib.batch_sharding(mesh)
    abstract_batch = {
        "tokens": jax.ShapeDtypeStruct((B, seq_len), jnp.int32,
                                       sharding=batch_sh),
        "targets": jax.ShapeDtypeStruct((B, seq_len), jnp.int32,
                                        sharding=batch_sh),
    }
    step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")),
                   donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        lowered = step.lower(abstract_state, abstract_batch)
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    arg_b = ma.argument_size_in_bytes
    out_b = ma.output_size_in_bytes
    temp_b = ma.temp_size_in_bytes
    alias_b = ma.alias_size_in_bytes
    # Donation aliases outputs onto arguments, so resident = args + temps
    # (outputs overlap args); without donation it would be args+outs+temps.
    resident = arg_b + temp_b
    return {
        "fsdp_devices": n_devices,
        "seq_len": seq_len,
        "global_batch": B,
        "n_params": n_params,
        "per_device": {
            "argument_bytes": arg_b,
            "output_bytes": out_b,
            "alias_bytes": alias_b,
            "temp_bytes": temp_b,
            "resident_bytes": resident,
            "resident_gb": round(resident / 1e9, 2),
        },
        "hbm_budget_gb": V5P_HBM_BYTES / 1e9,
        "fits": resident < V5P_HBM_BYTES,
        "headroom_gb": round((V5P_HBM_BYTES - resident) / 1e9, 2),
        "lower_s": round(t_lower, 1),
        "compile_s": round(t_compile, 1),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="FEASIBILITY_8B.json")
    p.add_argument("--seq-len", type=int, default=8192)
    args = p.parse_args(argv)

    rows = [analyze(16, args.seq_len), analyze(32, args.seq_len)]
    out = {
        "model": "llama3_8b",
        "strategy": "fsdp + per-block remat + scan_layers",
        "precision": "bf16 compute / fp32 params / adamw fp32 m+v",
        "memory_source": "jax compiled.memory_analysis() on XLA:CPU "
                         "(argument/output bytes are backend-independent; "
                         "temp bytes are XLA:CPU buffer assignment — an "
                         "approximation of XLA:TPU's)",
        "hardware_target": "v5p-32 (95 GB HBM/chip)",
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"rows": [{k: r[k] for k in
                                ("fsdp_devices", "fits")} | r["per_device"]
                               for r in rows], "out": args.out}))
    return 0


if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count={MAX_DEVICES}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The image's sitecustomize pins the axon TPU platform before env vars
    # are read; re-assert CPU through the config API (see launch docs).
    import jax

    jax.config.update("jax_platforms", "cpu")
    raise SystemExit(main())
