#!/usr/bin/env python
"""Llama-3 8B feasibility proof (VERDICT r2 #4; BASELINE.json configs[4]).

AOT-lowers and compiles the FULL fsdp+remat train step for ``llama3_8b`` on a
virtual CPU mesh (16 and 32 devices) with ABSTRACT inputs — no 32 GB of
parameters is ever materialized — and records the compiled executable's own
``memory_analysis()`` per-device byte counts against the v5p HBM budget
(95 GB/chip). This is the scaled-up version of the pattern
``tests/test_transformers.py::test_sp_reduces_activation_memory`` uses.

Caveat recorded in the artifact: the executable is compiled by the CPU
backend, so temp-buffer sizes reflect XLA:CPU's buffer assignment, not
XLA:TPU's (which fuses more aggressively); argument/output sizes (params,
optimizer state, batch) are backend-independent sharded-shape facts.

    python benchmarks/feasibility_8b.py [--out FEASIBILITY_8B.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5P_HBM_BYTES = 95e9
MAX_DEVICES = 32


def analyze(n_devices: int, seq_len: int, per_device_batch: int = 1,
            devices=None, mesh_spec=None, attn_impl="auto", remat=True):
    """One feasibility row: AOT-compile the 8B step and read its memory.

    ``mesh_spec`` overrides the default ``{"fsdp": n_devices}`` mesh for
    composed-topology rows (r22) — e.g. ``{"fsdp": 8, "context": 4}`` with
    ``attn_impl="ring"`` models sequence parallelism, where per-device
    activation temps scale ~1/seq and the ``[S, S]`` score block is never
    materialized."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, train_loop)
    from pytorch_distributed_training_example_tpu.core.train_state import TrainState
    from pytorch_distributed_training_example_tpu.models import llama as llama_lib
    from pytorch_distributed_training_example_tpu.parallel import (
        sharding as sharding_lib)
    from pytorch_distributed_training_example_tpu.utils.config import Config

    if devices is None:
        devices = jax.devices("cpu")[:n_devices]
    mesh = mesh_lib.build_mesh(mesh_spec or {"fsdp": n_devices},
                               devices=devices)
    module = llama_lib.llama3_8b(dtype=jnp.bfloat16, param_dtype=jnp.float32,
                                 remat=remat, scan_layers=True,
                                 attn_impl=attn_impl,
                                 max_seq_len=seq_len)
    n_params = llama_lib.num_params(module)
    tx, _ = optim.build_optimizer(
        Config(lr=3e-4, optimizer="adamw", weight_decay=0.1),
        steps_per_epoch=1000)
    rules = sharding_lib.strategy_rules("fsdp", llama_lib.TP_RULES)

    # Batch rows live on the data/fsdp axes only; seq/pp/ep replicate them.
    B = per_device_batch * mesh_lib.dp_size(mesh)
    tokens = jax.ShapeDtypeStruct((B, seq_len), jnp.int32)

    def init_fn(rng):
        variables = module.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                                train=False)
        return TrainState.create(apply_fn=module.apply,
                                 params=variables["params"], tx=tx,
                                 rng=jax.random.PRNGKey(0))

    t0 = time.perf_counter()
    state_shape = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = train_loop.state_shardings(state_shape, mesh, rules)
    abstract_state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_shape, shardings)
    batch_sh = mesh_lib.batch_sharding(mesh)
    abstract_batch = {
        "tokens": jax.ShapeDtypeStruct((B, seq_len), jnp.int32,
                                       sharding=batch_sh),
        "targets": jax.ShapeDtypeStruct((B, seq_len), jnp.int32,
                                        sharding=batch_sh),
    }
    step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")),
                   donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        lowered = step.lower(abstract_state, abstract_batch)
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    arg_b = ma.argument_size_in_bytes
    out_b = ma.output_size_in_bytes
    temp_b = ma.temp_size_in_bytes
    alias_b = ma.alias_size_in_bytes
    # Donation aliases outputs onto arguments, so resident = args + temps
    # (outputs overlap args); without donation it would be args+outs+temps.
    resident = arg_b + temp_b
    row_head = {"fsdp_devices": n_devices}
    if mesh_spec:
        row_head = {"mesh": dict(mesh_spec), "attn_impl": attn_impl,
                    "remat": remat}
    return {
        **row_head,
        "seq_len": seq_len,
        "global_batch": B,
        "n_params": n_params,
        "per_device": {
            "argument_bytes": arg_b,
            "output_bytes": out_b,
            "alias_bytes": alias_b,
            "temp_bytes": temp_b,
            "resident_bytes": resident,
            "resident_gb": round(resident / 1e9, 2),
        },
        "hbm_budget_gb": V5P_HBM_BYTES / 1e9,
        "fits": resident < V5P_HBM_BYTES,
        "headroom_gb": round((V5P_HBM_BYTES - resident) / 1e9, 2),
        "lower_s": round(t_lower, 1),
        "compile_s": round(t_compile, 1),
    }


def analyze_topology(topo_name: str, seq_len: int):
    """AOT-compile the full v5p program with XLA:TPU via a topology
    description (no v5p hardware needed) — the real buffer assignment for
    the real target, not a CPU approximation (VERDICT r3 missing #2)."""
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(topo_name)
    devices = list(topo.devices)
    row = analyze(len(devices), seq_len, devices=devices)
    row["compiler"] = f"XLA:TPU AOT topology {topo_name} ({devices[0].device_kind})"
    return row


def calibration_case(seq_len: int = 8192):
    """Same fsdp+remat train-step program at a scale that fits one v5e:
    llama_400m (full Llama block: GQA, RoPE, SwiGLU, RMSNorm; d_model 1024)
    at the 8B preset's own seq_len, batch 1, 1 device. Returns
    memory_analysis() numbers for whichever backend this process runs."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, train_loop)
    from pytorch_distributed_training_example_tpu.core.train_state import TrainState
    from pytorch_distributed_training_example_tpu.models import llama as llama_lib
    from pytorch_distributed_training_example_tpu.parallel import (
        sharding as sharding_lib)
    from pytorch_distributed_training_example_tpu.utils.config import Config

    mesh = mesh_lib.build_mesh({"fsdp": 1}, devices=jax.devices()[:1])
    module = llama_lib.llama_400m(dtype=jnp.bfloat16, param_dtype=jnp.float32,
                                  remat=True, scan_layers=True,
                                  max_seq_len=seq_len)
    tx, _ = optim.build_optimizer(
        Config(lr=3e-4, optimizer="adamw", weight_decay=0.1),
        steps_per_epoch=1000)
    rules = sharding_lib.strategy_rules("fsdp", llama_lib.TP_RULES)

    def init_fn(rng):
        variables = module.init({"params": rng}, jnp.zeros((1, 8), jnp.int32),
                                train=False)
        return TrainState.create(apply_fn=module.apply,
                                 params=variables["params"], tx=tx,
                                 rng=jax.random.PRNGKey(0))

    state_shape = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    shardings = train_loop.state_shardings(state_shape, mesh, rules)
    abstract_state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_shape, shardings)
    batch_sh = mesh_lib.batch_sharding(mesh)
    abstract_batch = {
        "tokens": jax.ShapeDtypeStruct((1, seq_len), jnp.int32,
                                       sharding=batch_sh),
        "targets": jax.ShapeDtypeStruct((1, seq_len), jnp.int32,
                                        sharding=batch_sh),
    }
    step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")),
                   donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        compiled = step.lower(abstract_state, abstract_batch).compile()
    ma = compiled.memory_analysis()
    return {
        "backend": jax.default_backend(),
        "device": jax.devices()[0].device_kind,
        "seq_len": seq_len,
        "argument_bytes": ma.argument_size_in_bytes,
        "temp_bytes": ma.temp_size_in_bytes,
        "temp_gb": round(ma.temp_size_in_bytes / 1e9, 3),
    }


def run_calibration(seq_len: int):
    """Compile the calibration case under XLA:CPU and XLA:TPU (separate
    processes — platform choice is process-wide) and report the temp-bytes
    ratio that converts CPU buffer-assignment temps into TPU ones."""
    import subprocess

    rows = {}
    for backend in ("cpu", "tpu"):
        env = dict(os.environ)
        if backend == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["FEAS_FORCE_CPU"] = "1"
        else:
            env.pop("JAX_PLATFORMS", None)
            env.pop("FEAS_FORCE_CPU", None)
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--calibrate-worker", "--seq-len", str(seq_len)],
            capture_output=True, text=True, env=env, timeout=1800)
        if res.returncode != 0:
            rows[backend] = {"error": (res.stderr or res.stdout)[-400:]}
            continue
        rows[backend] = json.loads(res.stdout.strip().splitlines()[-1])
    ratio = None
    if (all("temp_bytes" in rows.get(b, {}) for b in ("cpu", "tpu"))
            and rows["tpu"].get("backend") != "cpu"):
        # On a machine without a TPU the "tpu" worker silently falls back
        # to the CPU backend; a CPU/CPU ratio of ~1.0 must not be stamped
        # onto rows as "tpu_calibrated".
        ratio = rows["tpu"]["temp_bytes"] / max(rows["cpu"]["temp_bytes"], 1)
    return {"case": "llama_400m fsdp=1 remat seq_len=%d batch=1" % seq_len,
            "cpu": rows.get("cpu"), "tpu": rows.get("tpu"),
            "tpu_over_cpu_temp_ratio": round(ratio, 3) if ratio else None}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="FEASIBILITY_8B.json")
    p.add_argument("--seq-len", type=int, default=8192)
    p.add_argument("--no-calibrate", action="store_true",
                   help="skip the XLA:CPU-vs-TPU temp-bytes calibration")
    p.add_argument("--composed", action="store_true",
                   help="add/refresh the composed-topology memory model "
                        "(rows_composed: long-context fsdp x context rows, "
                        "ring vs unsharded) in an EXISTING --out artifact "
                        "without recompiling the base rows")
    p.add_argument("--calibrate-worker", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--topology-worker", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.calibrate_worker:
        print(json.dumps(calibration_case(args.seq_len)))
        return 0
    if args.topology_worker:
        print(json.dumps(analyze_topology(args.topology_worker, args.seq_len)))
        return 0

    if args.composed:
        # Composed-topology memory model (r22): the same 8B program at
        # S=32768 with the context axis in the mesh. The unsharded fsdp-32
        # row is the motivation — its modeled activation temps blow the
        # budget even under remat — and the fsdp x seq ring rows show the
        # ~1/seq per-device temp shrink that puts fsdp=4 x seq=8 under
        # budget once the measured CPU-vs-TPU temp ratio is applied.
        S = 32768
        rows_c = [
            analyze(MAX_DEVICES, S),
            analyze(MAX_DEVICES, S,
                    mesh_spec={"fsdp": 8, "context": 4}, attn_impl="ring"),
            analyze(MAX_DEVICES, S,
                    mesh_spec={"fsdp": 4, "context": 8}, attn_impl="ring"),
        ]
        with open(args.out) as f:
            doc = json.load(f)
        # 8B-scale CPU->TPU temp calibration from the artifact's own
        # matched pairs (rows_tpu_topology vs rows at the same fsdp
        # degree) — the 400m calibration ratio is documented as
        # non-transferable.
        pairs = [
            (t["per_device"]["temp_bytes"], c["per_device"]["temp_bytes"])
            for t in doc.get("rows_tpu_topology", []) if "per_device" in t
            for c in doc.get("rows", [])
            if c.get("fsdp_devices") == t.get("fsdp_devices")]
        ratio_8b = (round(sum(t / c for t, c in pairs) / len(pairs), 3)
                    if pairs else None)
        if ratio_8b:
            for row in rows_c:
                t = row["per_device"]["temp_bytes"] * ratio_8b
                resident = row["per_device"]["argument_bytes"] + t
                row["per_device"]["temp_bytes_tpu_calibrated"] = int(t)
                row["per_device"]["resident_gb_tpu_calibrated"] = round(
                    resident / 1e9, 2)
                row["fits_tpu_calibrated"] = resident < V5P_HBM_BYTES
        doc["rows_composed"] = {
            "_note": (
                "XLA:CPU memory_analysis at seq_len=32768 (same CPU "
                "buffer-assignment caveat as `rows`): the unsharded fsdp "
                "row exceeds the v5p budget on modeled bytes alone — "
                "calibrated OR raw — while ring attention over the "
                "context axis shards activations [B, S/seq, d] and never "
                "materializes the [S, S] score block, shrinking "
                "per-device temps ~1/seq (argument bytes grow as fsdp "
                "shrinks: params shard over fewer devices — the "
                "fsdp-vs-seq split is a real trade, and fsdp=4 x seq=8 "
                "is the first calibrated fit). tpu_calibrated columns "
                "use the 8B-scale temp ratio measured between this "
                "artifact's own XLA:TPU topology rows and their XLA:CPU "
                "twins. Gate lives in check_regression.py --aot-bytes "
                "(aot_seq_shrink)."),
            "tpu_over_cpu_temp_ratio_8b": ratio_8b,
            "rows": rows_c,
        }
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(json.dumps([{k: r[k] for k in ("seq_len", "fits")}
                          | {"mesh": r.get("mesh", {"fsdp": MAX_DEVICES})}
                          | r["per_device"] for r in rows_c]))
        return 0

    rows = [analyze(16, args.seq_len), analyze(32, args.seq_len)]

    # Primary result: real XLA:TPU buffer assignment via AOT topology
    # compiles of the actual v5p targets (v5p-32 = 16 chips = 2x2x4;
    # v5p-64 = 32 chips = 2x4x4), run in a TPU-backend subprocess.
    import subprocess
    topo_rows = []
    for topo in ("v5p:2x2x4", "v5p:2x4x4"):
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "FEAS_FORCE_CPU")}
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--topology-worker",
             topo, "--seq-len", str(args.seq_len)],
            capture_output=True, text=True, env=env, timeout=3600)
        if res.returncode != 0:
            topo_rows.append({"topology": topo,
                              "error": (res.stderr or res.stdout)[-400:]})
        else:
            topo_rows.append(json.loads(res.stdout.strip().splitlines()[-1]))
        print(json.dumps(topo_rows[-1])[:400], file=sys.stderr, flush=True)

    cal = None if args.no_calibrate else run_calibration(args.seq_len)
    topo_ok = [r for r in topo_rows if "per_device" in r]
    out = {
        "model": "llama3_8b",
        "strategy": "fsdp + per-block remat + scan_layers",
        "precision": "bf16 compute / fp32 params / adamw fp32 m+v",
        "memory_source": ("jax compiled.memory_analysis() from XLA:TPU AOT "
                          "topology compiles of the actual v5p targets "
                          "(primary, rows_tpu_topology); XLA:CPU rows kept "
                          "as a cross-check with a measured CPU-vs-TPU "
                          "temp-bytes calibration" if topo_ok else
                          "jax compiled.memory_analysis() on XLA:CPU, "
                          "calibrated against a real XLA:TPU compile at "
                          "v5e scale (topology AOT failed — see "
                          "rows_tpu_topology errors)"),
        "hardware_target": "v5p-32 (95 GB HBM/chip)",
        "rows_tpu_topology": topo_rows,
        "calibration": cal,
        "rows": rows,
    }
    if cal and cal.get("tpu_over_cpu_temp_ratio"):
        r = cal["tpu_over_cpu_temp_ratio"]
        for row in rows:
            t = row["per_device"]["temp_bytes"] * r
            resident = row["per_device"]["argument_bytes"] + t
            row["per_device"]["temp_bytes_tpu_calibrated"] = int(t)
            row["per_device"]["resident_gb_tpu_calibrated"] = round(
                resident / 1e9, 2)
            row["fits_tpu_calibrated"] = resident < V5P_HBM_BYTES
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "rows_tpu_topology": [
            {k: r[k] for k in ("fsdp_devices", "fits")} | r["per_device"]
            if "per_device" in r else r for r in topo_rows],
        "rows_cpu": [{k: r[k] for k in ("fsdp_devices", "fits")}
                     | r["per_device"] for r in rows],
        "calibration_ratio": (cal or {}).get("tpu_over_cpu_temp_ratio"),
        "out": args.out}))
    return 0


if __name__ == "__main__":
    # The TPU calibrate-worker must keep the real backend; everything else
    # (the 16/32-device AOT analysis, the CPU worker) runs on CPU fakes.
    _tpu_worker = ("--calibrate-worker" in sys.argv
                   and not os.environ.get("FEAS_FORCE_CPU"))
    if not _tpu_worker:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={MAX_DEVICES}").strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        # The image's sitecustomize pins the axon TPU platform before env
        # vars are read; re-assert CPU through the config API.
        import jax

        jax.config.update("jax_platforms", "cpu")
    raise SystemExit(main())
