#!/usr/bin/env python
"""graftlint: repo-specific two-layer static analysis.

Layer 1 (AST, stdlib-only, jax-free) walks the Python tree and enforces
hazard rules distilled from this repo's postmortems:

  GL001  zero-copy ``np.asarray``/``memoryview`` snapshots escaping into a
         background thread / async writer (the r11 checkpoint-corruption
         class; snapshots of donated device buffers must be ``np.array``
         copies).
  GL002  filesystem ops in checkpoint/resilience paths that bypass
         ``retriable_io`` (transient NFS/GCS-fuse errors must be retried
         or explicitly baselined).
  GL003  host-sync primitives (``jax.device_get``, ``.item()``,
         ``block_until_ready``, ``float()``/``int()`` of traced values)
         inside step-scope modules (train_loop / parallel / ops).
  GL004  knob-threading consistency: every ``utils/config.py`` field must
         be reachable from the ``main.py`` CLI, every CLI dest must map to
         a real Config field (``config_from_args`` silently drops
         strangers), and every perf knob threaded through
         ``bench.setup_step`` must be reachable from both ``bench.py`` and
         ``benchmarks/profile_step.py`` CLIs.
  GL005  wall-clock / unseeded randomness in seeded chaos & sampler paths
         (breaks same-seed ``chaos.jsonl`` diffing).

Layer 2 (IR) reuses the chipless abstract lowering behind
``profile_step.py --aot`` and inspects the optimized HLO / StableHLO of a
real bench program:

  GL101  donation coverage: state inputs not aliased to outputs
         (double-HBM residency).
  GL102  large fp32 ``convert`` results inside bf16-configured MoE regions
         (the r10 router-leak class, keyed on ``jax.named_scope`` tags).
  GL103  device-to-host transfers (host callbacks / outfeed) baked into
         the compiled step.
  GL104  sharding-constraint coverage per named-scope region; on a
         context>1 mesh the census also counts sequence-dim constraints
         (zero seq anchors at such a mesh is an error).
  GL105  unattributable point-to-point collectives: every ``all-to-all``
         (sanctioned scopes: ``moe_*`` for the EP dropless transport,
         ``attn_ulysses_a2a`` for Ulysses) and every
         ``collective-permute`` (``attn_ring_ppermute`` for the ring
         K/V rotation, ``pp_stage_shift`` for the GPipe hop, ``moe_*``
         for the EP ppermute fallback) in the compiled step must carry
         a sanctioned named-scope tag in its op_name metadata — an
         untagged collective evades the comms census (``--aot-bytes``)
         and the per-region profile rollups.

Findings are machine-readable (``--json``) and gated against a reviewed
suppression baseline (``benchmarks/lint_baseline.json``); each suppression
carries a one-line justification.  ``check_regression.py --lint`` wraps
this module for CI.

Usage:
  python benchmarks/graftlint.py                 # AST layer, gate vs baseline
  python benchmarks/graftlint.py --ir llama_moe_tiny
  python benchmarks/graftlint.py --all           # AST + IR (llama_moe_tiny)
  python benchmarks/graftlint.py --json          # machine-readable findings
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "pytorch_distributed_training_example_tpu"
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lint_baseline.json"
)

ERROR = "error"
INFO = "info"

# Region tags used by the AOT byte gate; the IR layer keys GL102/GL104 on
# the same vocabulary so findings line up with check_regression --aot-bytes.
# moe_experts_gmm is the dropless grouped-matmul kernel's inner scope
# (ops/grouped_matmul.py) — listed before moe_experts so a standalone
# occurrence classifies; nested occurrences resolve to the outer tag.
MOE_TAG_RE = re.compile(
    r"\bmoe_(router|dispatch|experts_gmm|experts|combine|aux)\b")

# Scopes sanctioned to issue all-to-all (GL105): the MoE EP transport
# regions and the Ulysses head<->sequence reshard (ops/attention.py). The
# moe_* alternatives mirror MOE_TAG_RE; cotangent a2as keep the forward
# scope path inside transpose(...), so backward ops match too.
A2A_SCOPE_RE = re.compile(
    r"\b(?:moe_(?:router|dispatch|experts_gmm|experts|combine|aux)"
    r"|attn_ulysses_a2a)\b")

# Scopes sanctioned to issue collective-permute (GL105, r22): the ring /
# zigzag K-V rotation and output un-permute (``attn_ring_ppermute``,
# ops/attention.py), the GPipe stage hop (``pp_stage_shift``,
# parallel/pipeline.py), and the moe_* EP ppermute fallback transport.
# ``attn_ring_allgather`` (the ring's dense fallback) rides along so an
# attention-site gather stays census-attributable too.
CPERM_SCOPE_RE = re.compile(
    r"\b(?:moe_(?:router|dispatch|experts_gmm|experts|combine|aux)"
    r"|attn_ring_ppermute|attn_ring_allgather|pp_stage_shift)\b")


def _norm(s: str) -> str:
    return re.sub(r"\s+", " ", s.strip())


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative posix path, or "<ir:label>" for IR findings
    line: int
    scope: str
    message: str
    severity: str = ERROR
    snippet: str = ""

    @property
    def fingerprint(self) -> str:
        return "|".join((self.rule, self.path, self.scope, _norm(self.snippet)))

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d

    def render(self) -> str:
        sev = "" if self.severity == ERROR else f" [{self.severity}]"
        return f"{self.path}:{self.line}: {self.rule}{sev} {self.message} (in {self.scope})"


# ---------------------------------------------------------------------------
# AST plumbing
# ---------------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPE_NODES = _FUNC_NODES + (ast.ClassDef,)


def _dotted(node) -> str | None:
    """'np.asarray' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Module:
    """Parsed module with parent links and dotted scope names."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath
        with open(os.path.join(root, relpath), "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=relpath)
        self.parent: dict[ast.AST, ast.AST] = {}
        self.scope_name: dict[ast.AST, str] = {self.tree: "<module>"}
        self._annotate(self.tree, "<module>")

    def _annotate(self, node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            self.parent[child] = node
            child_scope = scope
            if isinstance(child, _SCOPE_NODES):
                child_scope = (
                    child.name if scope == "<module>" else f"{scope}.{child.name}"
                )
            self.scope_name[child] = child_scope
            self._annotate(child, child_scope)

    def scope_of(self, node: ast.AST) -> str:
        # The scope a node *belongs to* is the name of its innermost
        # enclosing def/class (scope_name stores the scope the node opens,
        # for defs themselves, which is what we want for findings anyway).
        return self.scope_name.get(node, "<module>")

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule, node, message, severity=ERROR) -> Finding:
        return Finding(
            rule=rule,
            path=self.relpath,
            line=getattr(node, "lineno", 0),
            scope=self.scope_of(node),
            message=message,
            severity=severity,
            snippet=self.line_text(getattr(node, "lineno", 0)),
        )

    def statement_of(self, node: ast.AST) -> ast.stmt | None:
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parent.get(cur)
        return cur

    def enclosing_defs(self, node: ast.AST):
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                yield cur
            cur = self.parent.get(cur)


def _iter_own_nodes(unit: ast.AST):
    """All descendant nodes of `unit` that are not inside a nested def."""
    stack = list(ast.iter_child_nodes(unit))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FUNC_NODES):
            stack.extend(ast.iter_child_nodes(node))


def _scope_units(tree: ast.Module):
    """Yield (node,) for the module and every function at any depth."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES):
            yield node


def _bound_names(func: ast.AST) -> set[str]:
    bound: set[str] = set()
    if isinstance(func, _FUNC_NODES):
        a = func.args
        for arg in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        ):
            bound.add(arg.arg)
        if a.vararg:
            bound.add(a.vararg.arg)
        if a.kwarg:
            bound.add(a.kwarg.arg)
    for node in _iter_own_nodes(func):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
    return bound


def _free_reads(func: ast.AST) -> set[str]:
    bound = _bound_names(func)
    free: set[str] = set()
    for node in _iter_own_nodes(func):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id not in bound:
                free.add(node.id)
        elif isinstance(node, _FUNC_NODES):
            # Nested defs inherit the closure: their free reads are ours
            # too unless bound here.
            free |= {n for n in _free_reads(node) if n not in bound}
    return free


# ---------------------------------------------------------------------------
# GL001: zero-copy snapshots escaping to background threads
# ---------------------------------------------------------------------------

_ZERO_COPY = {"np.asarray", "numpy.asarray", "jnp.asarray", "memoryview"}
_MUTATORS = {"append", "extend", "add", "update", "setdefault", "insert", "put"}


def _gl001(mod: Module) -> list[Finding]:
    out: list[Finding] = []
    for unit in _scope_units(mod.tree):
        local_defs: dict[str, ast.AST] = {
            n.name: n for n in _iter_own_nodes(unit) if isinstance(n, _FUNC_NODES)
        }
        if not local_defs:
            continue
        # Thread / executor targets launched from this scope.
        target_names: set[str] = set()
        launch_calls: list[ast.Call] = []
        for node in _iter_own_nodes(unit):
            if not isinstance(node, ast.Call):
                continue
            callee = _dotted(node.func) or ""
            is_thread = callee.endswith("Thread") or callee.endswith("Process")
            is_submit = isinstance(node.func, ast.Attribute) and node.func.attr in (
                "submit",
                "apply_async",
            )
            if not (is_thread or is_submit):
                continue
            launch_calls.append(node)
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    target_names.add(kw.value.id)
            if is_submit and node.args and isinstance(node.args[0], ast.Name):
                target_names.add(node.args[0].id)
        async_defs = []
        seen: set[str] = set()
        frontier = [n for n in target_names if n in local_defs]
        while frontier:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            fn = local_defs[name]
            async_defs.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    if node.func.id in local_defs and node.func.id not in seen:
                        frontier.append(node.func.id)
        if not async_defs:
            continue
        free: set[str] = set()
        for fn in async_defs:
            free |= _free_reads(fn)
        async_nodes = set()
        for fn in async_defs:
            async_nodes.update(ast.walk(fn))
        # Pass 1: zero-copy calls whose results land directly in a name the
        # async defs read; also taint locals that hold the result (the real
        # r11 shape flowed through one: regions.append((idx, np.asarray(
        # sh.data))); ...; shards[path] = regions).
        flagged: set[ast.Call] = set()
        tainted: dict[str, ast.Call] = {}
        for node in _iter_own_nodes(unit):
            if node in async_nodes or not isinstance(node, ast.Call):
                continue
            callee = _dotted(node.func)
            if callee not in _ZERO_COPY:
                continue
            sink = _escape_sink(mod, node, free, launch_calls)
            if sink is not None:
                flagged.add(node)
                out.append(_gl001_finding(mod, node, callee, sink))
                continue
            stmt = mod.statement_of(node)
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        tainted[t.id] = node
            elif (
                isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr in _MUTATORS
                and isinstance(stmt.value.func.value, ast.Name)
            ):
                tainted[stmt.value.func.value.id] = node
        # Pass 2 (one hop): a tainted local flowing into a free name.
        for node in _iter_own_nodes(unit):
            if not tainted or node in async_nodes:
                continue
            sink, value = None, None
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    base = t
                    while isinstance(base, (ast.Subscript, ast.Attribute,
                                            ast.Starred)):
                        base = base.value
                    if isinstance(base, ast.Name) and base.id in free:
                        sink = base.id
                value = node.value
            elif (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr in _MUTATORS
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id in free
            ):
                sink = node.value.func.value.id
                value = node.value
            if sink is None or value is None:
                continue
            for name_node in ast.walk(value):
                if (
                    isinstance(name_node, ast.Name)
                    and name_node.id in tainted
                    and tainted[name_node.id] not in flagged
                    and not _consumed_between(mod, name_node, node)
                ):
                    call = tainted[name_node.id]
                    flagged.add(call)
                    out.append(
                        _gl001_finding(
                            mod, call, _dotted(call.func),
                            f"{name_node.id} -> {sink}"))
    return out


def _consumed_between(mod, node, stmt) -> bool:
    """True if a Call swallows `node`'s value between it and `stmt`'s
    assignment value / mutator args (copies like np.array(x) de-taint)."""
    anc = mod.parent.get(node)
    while anc is not None and anc is not stmt:
        if isinstance(anc, ast.Call):
            # The mutator call itself (free.append(tainted)) doesn't consume.
            parent = mod.parent.get(anc)
            is_stmt_call = (
                isinstance(stmt, ast.Expr) and anc is stmt.value
            )
            del parent
            return not is_stmt_call
        anc = mod.parent.get(anc)
    return False


def _gl001_finding(mod, node, callee, sink) -> Finding:
    return mod.finding(
        "GL001",
        node,
        f"zero-copy {callee}(...) escapes to a background thread via "
        f"'{sink}'; a donated/updated device buffer behind it can be "
        "overwritten mid-write — snapshot with np.array(...) instead "
        "(r11 corruption class)",
    )


def _escape_sink(mod, call, free, launch_calls):
    """Name through which `call`'s result reaches the async scope, or None."""
    # Direct argument of the Thread(...)/submit(...) launch itself.
    for lc in launch_calls:
        if any(call in ast.walk(a) for a in list(lc.args) + [k.value for k in lc.keywords]):
            return _dotted(lc.func) or "<launch>"
    stmt = mod.statement_of(call)
    if stmt is None:
        return None

    def consumed_before(outer) -> bool:
        # True if another call swallows the result between `call` and
        # `outer` (e.g. str(np.asarray(x).dtype)): no raw buffer escapes.
        anc = mod.parent.get(call)
        while anc is not None and anc is not outer:
            if isinstance(anc, ast.Call):
                return True
            anc = mod.parent.get(anc)
        return False

    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        if consumed_before(stmt):
            return None
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for t in targets:
            base = t
            while isinstance(base, (ast.Subscript, ast.Attribute, ast.Starred)):
                base = base.value
            if isinstance(base, ast.Name) and base.id in free:
                return base.id
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _MUTATORS
            and isinstance(f.value, ast.Name)
            and f.value.id in free
            and any(call in ast.walk(a) for a in stmt.value.args)
            and not consumed_before(stmt.value)
        ):
            return f.value.id
    return None


# ---------------------------------------------------------------------------
# GL002: fs ops bypassing retriable_io in checkpoint/resilience paths
# ---------------------------------------------------------------------------

GL002_PATHS = (f"{PKG}/core/checkpoint.py", f"{PKG}/utils/resilience.py",
               f"{PKG}/utils/scheduler.py", f"{PKG}/core/xcache.py",
               f"{PKG}/core/reshard.py", "launch.py")
_FS_OPS = {
    "open",
    "os.replace",
    "os.rename",
    "os.makedirs",
    "os.remove",
    "os.unlink",
    "os.rmdir",
    "os.listdir",
    "shutil.rmtree",
    "shutil.copy",
    "shutil.copy2",
    "shutil.copyfile",
    "shutil.move",
    "np.save",
    "np.load",
    "numpy.save",
    "numpy.load",
}


def _gl002(mod: Module) -> list[Finding]:
    if mod.relpath not in GL002_PATHS:
        return []
    # Functions whose *name* is handed to retriable_io anywhere in the
    # module are retry-wrapped at their call sites; their bodies are exempt.
    wrapped: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            callee = _dotted(node.func) or ""
            if callee.split(".")[-1] == "retriable_io" and node.args:
                first = _dotted(node.args[0])
                if first and "." not in first:
                    wrapped.add(first)
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee not in _FS_OPS:
            continue
        if callee == "shutil.rmtree" and any(
            kw.arg == "ignore_errors"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in node.keywords
        ):
            continue  # explicitly best-effort
        if any(d.name in wrapped or d.name == "retriable_io"
               for d in mod.enclosing_defs(node)):
            continue
        out.append(
            mod.finding(
                "GL002",
                node,
                f"filesystem op {callee}(...) in a checkpoint/resilience "
                "path bypasses retriable_io; transient NFS/object-store "
                "errors will abort the job instead of retrying",
            )
        )
    return out


# ---------------------------------------------------------------------------
# GL003: host-sync primitives in step-scope modules
# ---------------------------------------------------------------------------

GL003_PREFIXES = (f"{PKG}/core/train_loop.py", f"{PKG}/parallel/", f"{PKG}/ops/",
                  f"{PKG}/serve/")
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_SYNC_METHODS = {"item", "block_until_ready"}


def _gl003(mod: Module) -> list[Finding]:
    if not mod.relpath.startswith(GL003_PREFIXES):
        return []
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if any(d.name in ("main", "_selftest") for d in mod.enclosing_defs(node)):
            continue
        callee = _dotted(node.func)
        if callee in _SYNC_CALLS:
            out.append(
                mod.finding(
                    "GL003",
                    node,
                    f"host-sync {callee}(...) in a step-scope module blocks "
                    "the dispatch pipeline (device->host round trip inside "
                    "or around the jitted step)",
                )
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
            and not node.args
        ):
            out.append(
                mod.finding(
                    "GL003",
                    node,
                    f".{node.func.attr}() in a step-scope module forces a "
                    "host sync; keep metrics on-device and sync once per "
                    "logging interval",
                )
            )
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int")
            and len(node.args) == 1
            and isinstance(node.args[0], (ast.Call, ast.Subscript))
        ):
            out.append(
                mod.finding(
                    "GL003",
                    node,
                    f"{node.func.id}(...) of a computed value in a "
                    "step-scope module is a host sync if the operand is a "
                    "tracer/device array",
                    severity=INFO,
                )
            )
    return out


# ---------------------------------------------------------------------------
# GL004: knob-threading consistency across config/main/bench/profile_step
# ---------------------------------------------------------------------------

# CLI dests in main.py that intentionally do not map to Config fields
# (process bootstrap / composite parses).
GL004_INFRA_DESTS = {
    "distributed",
    "config",
    "mesh",
    "coordinator",
    "num_processes",
    "process_id",
    "platform",
    "fake_devices",
}


def _parser_dests(tree: ast.Module) -> dict[str, int]:
    dests: dict[str, int] = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            continue
        dest = None
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = kw.value.value
        if dest is None:
            for a in node.args:
                if (
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                    and a.value.startswith("--")
                ):
                    dest = a.value.lstrip("-").replace("-", "_")
                    break
        if dest:
            dests.setdefault(dest, node.lineno)
    return dests


def _kwarg_threads(tree: ast.Module) -> set[str]:
    """Keyword names passed anywhere as `name=args.<something>`."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                v = kw.value
                if (
                    isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "args"
                ):
                    out.add(kw.arg)
                elif isinstance(v, ast.Name) and v.id.startswith("args"):
                    out.add(kw.arg)
    return out


def _config_fields(tree: ast.Module) -> list[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return [
                s.target.id
                for s in node.body
                if isinstance(s, ast.AnnAssign)
                and isinstance(s.target, ast.Name)
                and not s.target.id.startswith("_")
            ]
    return []


def _func_params(tree: ast.Module, name: str) -> list[str]:
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES) and node.name == name:
            a = node.args
            return [p.arg for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    return []


def _gl004(root: str) -> list[Finding]:
    paths = {
        "config": f"{PKG}/utils/config.py",
        "main": "main.py",
        "bench": "bench.py",
        "profile": "benchmarks/profile_step.py",
    }
    mods: dict[str, Module] = {}
    for key, rel in paths.items():
        full = os.path.join(root, rel)
        if os.path.exists(full):
            mods[key] = Module(root, rel)
    if "config" not in mods or "main" not in mods:
        return []
    fields = _config_fields(mods["config"].tree)
    if not fields:
        return []
    out: list[Finding] = []
    cfg = mods["config"]
    main = mods["main"]
    main_dests = _parser_dests(main.tree)

    # Direction 1: every main.py CLI dest must be a Config field (or
    # declared infra), else config_from_args silently drops the override.
    for dest, lineno in sorted(main_dests.items()):
        if dest in fields or dest in GL004_INFRA_DESTS:
            continue
        out.append(
            Finding(
                rule="GL004",
                path=main.relpath,
                line=lineno,
                scope="build_parser",
                message=(
                    f"CLI dest '{dest}' is not a Config field; "
                    "config_from_args silently discards it (typo or "
                    "missing field)"
                ),
                snippet=main.line_text(lineno),
            )
        )

    # Direction 2: every Config field must be reachable from main.py.
    mesh_covered = "mesh" in main_dests
    for field in fields:
        if field.startswith("mesh_") and mesh_covered:
            continue  # composite --mesh AXIS=N parse covers mesh_* fields
        if field not in main_dests:
            out.append(
                Finding(
                    rule="GL004",
                    path=cfg.relpath,
                    line=1,
                    scope="Config",
                    message=(
                        f"Config field '{field}' has no main.py CLI flag; "
                        "it cannot be overridden without editing presets"
                    ),
                    snippet=field,
                )
            )

    # Direction 3: perf knobs threaded through bench.setup_step must be
    # reachable from bench.py and profile_step.py CLIs too.
    if "bench" in mods:
        knobs = [p for p in _func_params(mods["bench"].tree, "setup_step") if p in fields]
        for key in ("bench", "profile"):
            if key not in mods:
                continue
            m = mods[key]
            dests = _parser_dests(m.tree)
            threaded = _kwarg_threads(m.tree)
            for knob in knobs:
                if knob in dests or knob in threaded:
                    continue
                out.append(
                    Finding(
                        rule="GL004",
                        path=m.relpath,
                        line=1,
                        scope="<cli>",
                        message=(
                            f"perf knob '{knob}' (bench.setup_step param and "
                            f"Config field) is not reachable from the "
                            f"{os.path.basename(m.relpath)} CLI"
                        ),
                        snippet=knob,
                    )
                )
    return out


# ---------------------------------------------------------------------------
# GL005: wall-clock / unseeded randomness in seeded chaos & sampler paths
# ---------------------------------------------------------------------------

GL005_PATHS = (f"{PKG}/utils/chaos.py", f"{PKG}/data/sampler.py",
               f"{PKG}/serve/engine.py", f"{PKG}/serve/loadgen.py",
               f"{PKG}/serve/prefix_cache.py", f"{PKG}/serve/router.py",
               f"{PKG}/serve/slo.py", f"{PKG}/serve/spec_decode.py",
               f"{PKG}/utils/scheduler.py", f"{PKG}/core/reshard.py",
               f"{PKG}/core/xcache.py", "launch.py")
_NP_UNSEEDED = {
    "rand",
    "randn",
    "randint",
    "random",
    "random_sample",
    "choice",
    "shuffle",
    "permutation",
    "uniform",
    "normal",
    "seed",
}


def _gl005(mod: Module) -> list[Finding]:
    if mod.relpath not in GL005_PATHS:
        return []
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func) or ""
        bad = None
        if callee in ("time.time", "time.time_ns", "datetime.now", "datetime.datetime.now"):
            bad = f"wall-clock {callee}() in a seeded path makes same-seed runs diverge"
        elif callee.startswith("random."):
            bad = f"unseeded stdlib {callee}(...) breaks same-seed chaos.jsonl diffing"
        elif (
            callee.startswith(("np.random.", "numpy.random."))
            and callee.split(".")[-1] in _NP_UNSEEDED
        ):
            bad = (
                f"global-state {callee}(...) is unseeded; use a "
                "np.random.default_rng/RandomState seeded from cfg"
            )
        if bad:
            out.append(mod.finding("GL005", node, bad))
    return out


# ---------------------------------------------------------------------------
# AST driver
# ---------------------------------------------------------------------------

EXCLUDE_DIRS = {"__pycache__", "tests", "native", ".git", ".venv", "fixtures"}


def collect_py_files(root: str) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in EXCLUDE_DIRS and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                out.append(rel.replace(os.sep, "/"))
    return out


def run_ast(root: str = REPO_ROOT, files: list[str] | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for rel in files if files is not None else collect_py_files(root):
        try:
            mod = Module(root, rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(
                Finding(
                    rule="GL000",
                    path=rel,
                    line=getattr(e, "lineno", 0) or 0,
                    scope="<module>",
                    message=f"unparseable: {e}",
                    snippet="",
                )
            )
            continue
        findings += _gl001(mod)
        findings += _gl002(mod)
        findings += _gl003(mod)
        findings += _gl005(mod)
    findings += _gl004(root)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# IR layer (lazy jax import; reuses profile_step's abstract lowering)
# ---------------------------------------------------------------------------

def _entry_block(hlo: str) -> str:
    m = re.search(r"^ENTRY\b.*$", hlo, re.M)
    if not m:
        return hlo
    rest = hlo[m.start():]
    end = re.search(r"^\}", rest, re.M)
    return rest[: end.end()] if end else rest


def _aliased_params(hlo: str) -> set[int]:
    m = re.search(r"input_output_alias=\{", hlo)
    if not m:
        return set()
    depth, i = 1, m.end()
    while i < len(hlo) and depth:
        if hlo[i] == "{":
            depth += 1
        elif hlo[i] == "}":
            depth -= 1
        i += 1
    body = hlo[m.end(): i - 1]
    return {int(p) for p in re.findall(r"\((\d+),", body)}


def _leaf_bytes(leaf) -> int:
    import numpy as _np

    try:
        return int(_np.dtype(leaf.dtype).itemsize * _np.prod(leaf.shape, dtype=_np.int64))
    except Exception:
        return 0


def _ir_donation(hlo, label, abstract_state, slack) -> list[Finding]:
    import jax

    leaves_with_paths = jax.tree_util.tree_flatten_with_path(abstract_state)[0]
    n_state = len(leaves_with_paths)
    aliased = _aliased_params(hlo)
    entry = _entry_block(hlo)
    n_params = len(set(re.findall(r"parameter\((\d+)\)", entry)))
    out: list[Finding] = []
    if n_params < n_state:
        out.append(
            Finding(
                rule="GL101",
                path=f"<ir:{label}>",
                line=0,
                scope="donation",
                message=(
                    f"entry param count {n_params} < state leaf count "
                    f"{n_state}; param mapping uncertain, donation coverage "
                    "checked by count only"
                ),
                severity=INFO,
                snippet="param-mapping",
            )
        )
    missing = [
        (jax.tree_util.keystr(path), _leaf_bytes(leaf))
        for i, (path, leaf) in enumerate(leaves_with_paths)
        if i not in aliased
    ]
    total = sum(_leaf_bytes(leaf) for _, leaf in leaves_with_paths) or 1
    missing_bytes = sum(b for _, b in missing)
    if missing and missing_bytes > slack * total:
        worst = sorted(missing, key=lambda kv: -kv[1])[:5]
        detail = ", ".join(f"{k} ({b/1e6:.2f} MB)" for k, b in worst)
        out.append(
            Finding(
                rule="GL101",
                path=f"<ir:{label}>",
                line=0,
                scope="donation",
                message=(
                    f"{len(missing)}/{n_state} state inputs "
                    f"({missing_bytes/1e6:.2f} of {total/1e6:.2f} MB) are "
                    f"not aliased to outputs — donation gap doubles HBM "
                    f"residency for: {detail}"
                ),
                snippet=f"non-donated={len(missing)}",
            )
        )
    elif missing:
        out.append(
            Finding(
                rule="GL101",
                path=f"<ir:{label}>",
                line=0,
                scope="donation",
                message=(
                    f"{len(missing)}/{n_state} state inputs not aliased "
                    f"({missing_bytes} B, under {slack:.0%} slack): "
                    + ", ".join(k for k, _ in missing[:5])
                ),
                severity=INFO,
                snippet=f"non-donated-small={len(missing)}",
            )
        )
    return out


_CONVERT_RE = re.compile(
    r"= f32\[([\d,]*)\](?:\{[^}]*\})? convert\(.*?op_name=\"([^\"]+)\"", re.S
)


def _ir_upcast(hlo, label, upcast_bytes) -> list[Finding]:
    out: list[Finding] = []
    seen: set[tuple] = set()
    for line in hlo.splitlines():
        m = _CONVERT_RE.search(line)
        if not m:
            continue
        shape_s, op_name = m.groups()
        tag_m = MOE_TAG_RE.search(op_name)
        if not tag_m:
            continue
        # Backward-pass converts (transpose(jvp(...)) scopes) are the
        # mixed-precision grad->fp32-optimizer upcasts, one per param leaf
        # by design; the r10 leak class is *forward* ops computing wide.
        if "transpose(" in op_name:
            continue
        # Only source-level casts/promotions (jaxpr convert_element_type)
        # count: XLA materializes operand upcasts for f32-ACCUMULATING bf16
        # dots (preferred_element_type) and attributes them to the dot op —
        # that is the accumulation contract working, not a leak.
        if not op_name.endswith("convert_element_type"):
            continue
        dims = [int(d) for d in shape_s.split(",") if d] or [1]
        nbytes = 4
        for d in dims:
            nbytes *= d
        if nbytes < upcast_bytes:
            continue
        region = tag_m.group(0)
        key = (region, shape_s)
        if key in seen:
            continue
        seen.add(key)
        out.append(
            Finding(
                rule="GL102",
                path=f"<ir:{label}>",
                line=0,
                scope=region,
                message=(
                    f"fp32 convert to f32[{shape_s}] ({nbytes/1e6:.2f} MB) "
                    f"inside bf16 region '{region}' (op {op_name}) — the "
                    "r10 router-leak class; keep wide math scoped to the "
                    "router softmax or raise the region's declared dtype"
                ),
                snippet=f"convert f32[{shape_s}] {region}",
            )
        )
    return out


def _ir_host_transfer(hlo, label) -> list[Finding]:
    out: list[Finding] = []
    for line in hlo.splitlines():
        hit = None
        m = re.search(r'custom_call_target="([^"]+)"', line)
        if m and ("callback" in m.group(1) or "host" in m.group(1).lower()):
            hit = f"host callback custom-call '{m.group(1)}'"
        elif re.search(r"= \S+ (outfeed|infeed)\(", line):
            hit = "outfeed/infeed"
        if hit is None:
            continue
        op = re.search(r'op_name="([^"]+)"', line)
        out.append(
            Finding(
                rule="GL103",
                path=f"<ir:{label}>",
                line=0,
                scope="host-transfer",
                message=(
                    f"{hit} inside the compiled step"
                    + (f" (op {op.group(1)})" if op else "")
                    + " — device->host transfer serializes every step"
                ),
                snippet=_norm(hit),
            )
        )
    return out


def _ir_sharding(asm, label, expect_sharding, seq_axis=False) -> list[Finding]:
    locs: dict[str, str] = {}
    for m in re.finditer(r"#loc(\d+) = loc\(\"([^\"]+)\"", asm):
        locs[m.group(1)] = m.group(2)
    # Aliased locs: #loc12 = loc(#loc7)
    for m in re.finditer(r"#loc(\d+) = loc\(#loc(\d+)\)", asm):
        if m.group(2) in locs:
            locs.setdefault(m.group(1), locs[m.group(2)])
    counts: dict[str, int] = {}
    total = 0
    seq_total = 0
    for m in re.finditer(
        r"stablehlo\.custom_call\s+@Sharding.*?loc\(#loc(\d+)\)", asm
    ):
        total += 1
        scope_s = locs.get(m.group(1), "")
        tag = MOE_TAG_RE.search(scope_s)
        region = tag.group(0) if tag else "untagged"
        counts[region] = counts.get(region, 0) + 1
        # Sequence-axis census (r22): a constraint splitting dim 1 of a
        # rank>=3 operand is anchoring the [B, S, ...] sequence dim — on a
        # context>1 mesh that's the seq/context axis (plus "model" when the
        # Megatron-SP fold is on). devices=[a,b,...] lists the per-dim tile
        # factors in dim order, so dim 1's factor is the second entry.
        dev = re.search(r'mhlo\.sharding = "[^"]*devices=\[(\d+),(\d+)',
                        m.group(0))
        rank = re.search(r"tensor<(?:\d+x){3,}", m.group(0))
        if dev and rank and int(dev.group(2)) > 1:
            seq_total += 1
    out: list[Finding] = []
    if seq_axis and total and seq_total == 0:
        out.append(
            Finding(
                rule="GL104",
                path=f"<ir:{label}>",
                line=0,
                scope="sharding",
                message=(
                    "mesh has a context axis but no sharding constraint "
                    "splits the sequence dim — activations are unanchored "
                    "on seq; propagation may replicate [B, S, d] residuals "
                    "(wire the models' seq_rules constrain sites)"
                ),
                snippet="seq-constraints=0",
            )
        )
    if total == 0 and expect_sharding:
        out.append(
            Finding(
                rule="GL104",
                path=f"<ir:{label}>",
                line=0,
                scope="sharding",
                message=(
                    "no sharding constraints in the lowered program on a "
                    ">1-device mesh — GSPMD has no anchors; intermediate "
                    "layouts are left entirely to sharding propagation"
                ),
                snippet="sharding-constraints=0",
            )
        )
    else:
        detail = ", ".join(f"{k}={v}" for k, v in sorted(counts.items())) or "none"
        if seq_axis:
            detail += f", seq-dim={seq_total}"
        out.append(
            Finding(
                rule="GL104",
                path=f"<ir:{label}>",
                line=0,
                scope="sharding",
                message=f"sharding-constraint coverage per region: {detail} (total {total})",
                severity=INFO,
                snippet=f"coverage total={total}",
            )
        )
    return out


_A2A_LINE_RE = re.compile(r"= (?:\([^)]*\)|\S+) all-to-all(?:-start)?\(")
_CPERM_LINE_RE = re.compile(
    r"= (?:\([^)]*\)|\S+) collective-permute(?:-start)?\(")


def _ir_a2a_scope(hlo, label) -> list[Finding]:
    """GL105: point-to-point collectives outside sanctioned named scopes.

    The comms census (profile_step.collective_byte_census) and the
    PROFILE_MOE region rollups attribute traffic by named-scope tag; a
    collective issued outside a sanctioned scope lands in ``non_moe``
    where the --aot-bytes golden never gates it. Two opcodes are policed:
    ``all-to-all`` (sanctioned: ``moe_*`` EP transport,
    ``attn_ulysses_a2a``) and, since the ring/pipeline axes (r22),
    ``collective-permute`` (sanctioned: ``attn_ring_ppermute``,
    ``pp_stage_shift``, ``moe_*`` ppermute fallback). All-gather is NOT
    policed — GSPMD's FSDP weight gathers are legitimately everywhere —
    but the ring's dense fallback tags its gathers ``attn_ring_allgather``
    so they classify. -done halves are skipped (same instruction, counted
    once at -start or the sync op).
    """
    out: list[Finding] = []
    seen: set[str] = set()
    policed = (("all-to-all", _A2A_LINE_RE, A2A_SCOPE_RE,
                "jax.named_scope('moe_dispatch'/'attn_ulysses_a2a')"),
               ("collective-permute", _CPERM_LINE_RE, CPERM_SCOPE_RE,
                "jax.named_scope('attn_ring_ppermute'/'pp_stage_shift')"))
    for line in hlo.splitlines():
        for opcode, line_re, scope_re, hint in policed:
            if not line_re.search(line):
                continue
            op = re.search(r'op_name="([^"]+)"', line)
            op_name = op.group(1) if op else ""
            if op_name and scope_re.search(op_name):
                continue
            key = f"{opcode} " + (_norm(op_name) or "<no-op_name>")
            if key in seen:
                continue
            seen.add(key)
            out.append(
                Finding(
                    rule="GL105",
                    path=f"<ir:{label}>",
                    line=0,
                    scope="a2a-scope",
                    message=(
                        f"{opcode} outside sanctioned named scopes "
                        f"(op {op_name or '<untagged>'}) — wrap the call "
                        f"site in {hint} so the comms census and region "
                        "rollups can attribute its bytes"
                    ),
                    snippet=key if opcode != "all-to-all"
                    else f"a2a {_norm(op_name) or '<no-op_name>'}",
                )
            )
    return out


def lint_lowered(
    label: str,
    lowered,
    *,
    abstract_state=None,
    bf16_regions: bool = True,
    upcast_bytes: int = 1 << 20,
    donation_slack: float = 0.01,
    expect_sharding: bool | None = None,
    seq_axis: bool = False,
) -> list[Finding]:
    """IR rules on an already-lowered jitted step (test-facing hook).

    ``seq_axis=True`` (a context>1 mesh) arms GL104's sequence-dim census:
    zero seq-splitting constraints at such a mesh is an error."""
    compiled = lowered.compile()
    hlo = compiled.as_text()
    findings: list[Finding] = []
    if abstract_state is not None:
        findings += _ir_donation(hlo, label, abstract_state, donation_slack)
    if bf16_regions:
        findings += _ir_upcast(hlo, label, upcast_bytes)
    findings += _ir_host_transfer(hlo, label)
    findings += _ir_a2a_scope(hlo, label)
    try:
        asm = lowered.compiler_ir(dialect="stablehlo").operation.get_asm(
            enable_debug_info=True
        )
    except Exception:
        asm = ""
    if asm:
        findings += _ir_sharding(asm, label, bool(expect_sharding),
                                 seq_axis=seq_axis)
    return findings


def run_ir(
    model: str = "llama_moe_tiny",
    *,
    per_chip_batch: int = 2,
    seq_len: int = 64,
    precision: str = "bf16",
    upcast_bytes: int = 1 << 20,
    donation_slack: float = 0.01,
    **knobs,
) -> list[Finding]:
    """Lower a real bench program chiplessly and run the IR rules on it."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import profile_step

    built = profile_step.build_abstract_step(
        model,
        per_chip_batch=per_chip_batch,
        precision=precision,
        seq_len=seq_len,
        **knobs,
    )
    import pytorch_distributed_training_example_tpu.core.mesh as mesh_lib

    with mesh_lib.use_mesh(built["mesh"]):
        lowered = built["step"].lower(built["abstract_state"], built["abstract_batch"])
        return lint_lowered(
            model,
            lowered,
            abstract_state=built["abstract_state"],
            bf16_regions=precision in ("bf16", "mixed"),
            upcast_bytes=upcast_bytes,
            donation_slack=donation_slack,
            expect_sharding=built["mesh"].size > 1,
            seq_axis=built["mesh"].shape.get("context", 1) > 1,
        )


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str = DEFAULT_BASELINE) -> dict:
    if not os.path.exists(path):
        return {"suppressions": []}
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _sup_key(entry: dict) -> str:
    return "|".join(
        (
            entry.get("rule", ""),
            entry.get("path", ""),
            entry.get("scope", ""),
            _norm(entry.get("snippet", "")),
        )
    )


def split_findings(findings: list[Finding], baseline: dict):
    """-> (unbaselined, baselined, stale_suppressions)."""
    sups = {_sup_key(s): s for s in baseline.get("suppressions", [])}
    used: set[str] = set()
    unbaselined, baselined = [], []
    for f in findings:
        if f.fingerprint in sups:
            used.add(f.fingerprint)
            baselined.append(f)
        else:
            unbaselined.append(f)
    stale = [s for k, s in sups.items() if k not in used]
    return unbaselined, baselined, stale


def record_baseline(findings: list[Finding], path: str = DEFAULT_BASELINE) -> dict:
    """Refresh the baseline, preserving reviewed justifications."""
    old = load_baseline(path)
    old_by_key = {_sup_key(s): s for s in old.get("suppressions", [])}
    sups = []
    for f in findings:
        if f.severity != ERROR:
            continue
        prev = old_by_key.get(f.fingerprint)
        sups.append(
            {
                "rule": f.rule,
                "path": f.path,
                "scope": f.scope,
                "snippet": _norm(f.snippet),
                "justification": (
                    prev["justification"]
                    if prev and not prev.get("justification", "").startswith("UNREVIEWED")
                    else f"UNREVIEWED: {f.message[:100]}"
                ),
            }
        )
    doc = {
        "_comment": (
            "Reviewed graftlint suppressions. Every entry needs a one-line "
            "justification; refresh with check_regression.py --lint --record "
            "(new entries land as UNREVIEWED and must be edited by hand)."
        ),
        "suppressions": sups,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return doc


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="repo-specific two-layer linter")
    p.add_argument("--root", default=REPO_ROOT, help="tree to lint (AST layer)")
    p.add_argument("--ir", metavar="MODEL", default=None, help="run IR rules on MODEL")
    p.add_argument("--all", action="store_true", help="AST + IR on llama_moe_tiny")
    p.add_argument("--no-ast", action="store_true", help="skip the AST layer")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--no-baseline", action="store_true", help="ignore the baseline")
    p.add_argument("--write-baseline", action="store_true", help="refresh suppressions")
    p.add_argument("--ir-seq-len", type=int, default=64)
    p.add_argument("--ir-batch", type=int, default=2)
    p.add_argument("--ir-precision", default="bf16")
    p.add_argument("--ir-upcast-bytes", type=int, default=1 << 20)
    args = p.parse_args(argv)

    findings: list[Finding] = []
    if not args.no_ast:
        findings += run_ast(os.path.abspath(args.root))
    ir_model = args.ir or ("llama_moe_tiny" if args.all else None)
    if ir_model:
        findings += run_ir(
            ir_model,
            per_chip_batch=args.ir_batch,
            seq_len=args.ir_seq_len,
            precision=args.ir_precision,
            upcast_bytes=args.ir_upcast_bytes,
        )

    baseline = {"suppressions": []} if args.no_baseline else load_baseline(args.baseline)
    unbaselined, baselined, stale = split_findings(findings, baseline)
    gate = [f for f in unbaselined if f.severity == ERROR]

    if args.write_baseline:
        record_baseline(findings, args.baseline)
        print(f"graftlint: wrote {args.baseline}")
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    "counts": {
                        "total": len(findings),
                        "errors": sum(1 for f in findings if f.severity == ERROR),
                        "baselined": len(baselined),
                        "unbaselined_errors": len(gate),
                        "stale_suppressions": len(stale),
                    },
                    "stale_suppressions": stale,
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            mark = "  [baselined]" if f in baselined else ""
            print(f.render() + mark)
        for s in stale:
            print(f"graftlint: stale suppression (code moved?): {_sup_key(s)}")
        print(
            f"graftlint: {len(findings)} finding(s), {len(baselined)} baselined, "
            f"{len(gate)} unbaselined error(s), {len(stale)} stale suppression(s)"
        )
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
