#!/usr/bin/env python
"""Merge per-rank, per-attempt telemetry into one fleet view.

Inputs (all under one checkpoint/log directory, written by
``utils/telemetry.py``):

- ``trace_events.r<R>.a<A>.json`` (+ legacy plain ``trace_events.json``)
- ``goodput.r<R>.a<A>.json``      (+ legacy plain ``goodput.json``)
- ``steprows.r<R>.a<A>.jsonl``    (per-step host timings, log-cadence flushed)
- ``reqtrace.<replica>.a<A>[.g<N>].json`` — serving request spans
  (``serve/slo.py``); each replica becomes a ``host/serve:<replica>``
  track group with per-role lanes (prefill/decode/router)

Outputs:

- ``merged_trace.json``  — one clock-aligned Perfetto/Chrome trace: each
  (host, rank) becomes a process track group (named via ``process_name``
  metadata events), attempts stack on the shared wall clock, and restart
  badput gaps appear as explicit ``restart`` slices.
- ``fleet_goodput.json`` — per-rank cumulative goodput folded into one fleet
  summary (``utils/fleetobs.aggregate_goodput``).
- ``straggler.jsonl``    — per-step skew attribution across ranks
  (``utils/fleetobs.detect_stragglers``).

Clock alignment: every trace stamps a monotonic<->wall anchor captured at
recorder construction. Event ``ts`` values are microseconds after that
host's monotonic origin; shifting each file by ``(wall_origin -
min(wall_origins)) * 1e6`` puts all ranks and attempts on one axis whose
zero is the earliest attempt's start. Host clocks are NTP-close (ms), which
is plenty for second-scale spans.

Torn files: a host killed mid-write (chaos ``kill_host``, real hardware
loss) leaves a truncated JSON. Because the writer puts ``otherData`` FIRST,
the salvage walks back from the end of the buffer trying successively
shorter prefixes closed with ``]}`` — recovering the header and every
complete event, exactly the spirit of ``utils/elastic.read_dead_hosts``.

Exits non-zero (loudly) when artifacts from DIFFERENT runs are mixed in one
directory, unless ``--allow-mixed-run`` is given.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_training_example_tpu.utils import fleetobs  # noqa: E402

MERGED_TRACE = "merged_trace.json"
FLEET_GOODPUT = "fleet_goodput.json"

_TRACE_RE = re.compile(r"trace_events\.r(\d+)\.a(\d+)\.json$")
_GOODPUT_RE = re.compile(r"goodput\.r(\d+)\.a(\d+)\.json$")
# Serving request traces (serve/slo.py RequestTrace): per-replica, with
# optional ring-rotation generations (".g<N>").
_REQTRACE_RE = re.compile(
    r"reqtrace\.([A-Za-z0-9_.-]+?)\.a(\d+)(?:\.g(\d+))?\.json$")


def load_trace_salvage(path: str) -> dict | None:
    """Parse a (possibly torn) trace file; None when nothing is salvageable.

    Fast path: plain ``json.load``. Torn path: try successively shorter
    prefixes ending at a ``}`` (an event boundary), closing the events array
    and the root object — keeps the header and all complete events.
    """
    try:
        with open(path) as fh:
            raw = fh.read()
    except OSError:
        return None
    try:
        doc = json.loads(raw)
        return doc if isinstance(doc, dict) else None
    except ValueError:
        pass
    end = len(raw)
    for _ in range(4096):  # bounded: one step back per damaged event
        cut = raw.rfind("}", 0, end)
        if cut < 0:
            return None
        for closer in ("]}", "}"):  # torn inside events vs inside header
            try:
                doc = json.loads(raw[:cut + 1] + closer)
            except ValueError:
                continue
            if isinstance(doc, dict):
                doc["_salvaged"] = True
                return doc
        end = cut
    return None


def discover(directory: str) -> dict[tuple[int, int], str]:
    """(rank, attempt) -> trace path. Suffixed files win; the legacy plain
    file fills in rank 0 only when no suffixed rank-0 file exists."""
    found: dict[tuple[int, int], str] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return {}
    for name in names:
        m = _TRACE_RE.fullmatch(name)
        if m:
            found[(int(m.group(1)), int(m.group(2)))] = os.path.join(
                directory, name)
    if not any(r == 0 for r, _ in found):
        plain = os.path.join(directory, "trace_events.json")
        if os.path.exists(plain):
            found[(0, 1)] = plain
    return found


def discover_reqtraces(directory: str) -> dict[tuple[str, int, int], str]:
    """(replica, attempt, generation) -> request-trace path. The live
    snapshot (no ``.g<N>`` suffix) sorts as generation 2**31 so rotated
    generations replay in write order before it."""
    found: dict[tuple[str, int, int], str] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return {}
    for name in names:
        m = _REQTRACE_RE.fullmatch(name)
        if m:
            gen = int(m.group(3)) if m.group(3) is not None else 2**31
            found[(m.group(1), int(m.group(2)), gen)] = os.path.join(
                directory, name)
    return found


def _anchor_wall(doc: dict) -> float | None:
    anchor = (doc.get("otherData") or {}).get("clock_anchor") or {}
    try:
        return float(anchor["wall"])
    except (KeyError, TypeError, ValueError):
        return None


def merge_traces(directory: str, *, allow_mixed_run: bool = False) -> dict:
    """Build the merged, clock-aligned trace dict (see module docstring)."""
    paths = discover(directory)
    docs: dict[tuple[int, int], dict] = {}
    for key in sorted(paths):
        doc = load_trace_salvage(paths[key])
        if doc is None:
            print(f"trace_merge: {paths[key]} unsalvageable — skipped",
                  file=sys.stderr)
            continue
        docs[key] = doc
    req_paths = discover_reqtraces(directory)
    req_docs: dict[tuple[str, int, int], dict] = {}
    for key in sorted(req_paths):
        doc = load_trace_salvage(req_paths[key])
        if doc is None:
            print(f"trace_merge: {req_paths[key]} unsalvageable — skipped",
                  file=sys.stderr)
            continue
        req_docs[key] = doc
    if not docs and not req_docs:
        raise SystemExit(f"trace_merge: no readable trace files in "
                         f"{directory!r}")

    all_docs = list(docs.values()) + list(req_docs.values())
    run_ids = sorted({(d.get("otherData") or {}).get("run_id") or "<unstamped>"
                      for d in all_docs})
    if len(run_ids) > 1 and not allow_mixed_run:
        raise SystemExit(
            f"trace_merge: refusing to merge artifacts from {len(run_ids)} "
            f"different runs {run_ids} in {directory!r} — stale files from a "
            f"previous experiment? (--allow-mixed-run to override)")

    # Wall anchors: earliest one is the merged time origin. Unanchored
    # (legacy) docs sit at offset 0 — their spans still render, unaligned.
    walls = [w for w in (_anchor_wall(d) for d in all_docs)
             if w is not None]
    origin = min(walls) if walls else 0.0

    events: list[dict] = []
    pid_by_group: dict[tuple[str, int], int] = {}
    for (rank, attempt), doc in sorted(docs.items()):
        other = doc.get("otherData") or {}
        host = other.get("host") or "host"
        group = (host, int(other.get("rank", rank)))
        if group not in pid_by_group:
            pid = len(pid_by_group) + 1
            pid_by_group[group] = pid
            events.append({  # Perfetto track-group label
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"{group[0]}/rank{group[1]}"}})
        pid = pid_by_group[group]
        wall = _anchor_wall(doc)
        shift_us = int(((wall - origin) if wall is not None else 0.0) * 1e6)
        for ev in doc.get("traceEvents") or []:
            if not isinstance(ev, dict) or "ts" not in ev:
                continue
            out = dict(ev)
            out["ts"] = int(ev["ts"]) + shift_us
            out["pid"] = pid
            if attempt > 1:
                out.setdefault("args", {})
                out["args"] = {**out["args"], "attempt": attempt}
            events.append(out)

    # Serving request traces: one track group per (host, replica), sitting
    # next to the training ranks. Role lanes (prefill/decode/router) are
    # the tids RequestTrace stamped; name them from the doc's roles map.
    serve_pids: dict[str, int] = {}
    dropped_spans = 0
    for (replica, attempt, gen), doc in sorted(req_docs.items()):
        other = doc.get("otherData") or {}
        host = other.get("host") or "host"
        label = f"{host}/serve:{replica}"
        if label not in serve_pids:
            pid = len(pid_by_group) + len(serve_pids) + 1
            serve_pids[label] = pid
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": label}})
            for tid, role in sorted((other.get("roles") or {}).items(),
                                    key=lambda kv: int(kv[0])):
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": int(tid), "args": {"name": str(role)}})
        pid = serve_pids[label]
        try:
            dropped_spans += int(other.get("dropped_spans") or 0)
        except (TypeError, ValueError):
            pass
        wall = _anchor_wall(doc)
        shift_us = int(((wall - origin) if wall is not None else 0.0) * 1e6)
        for ev in doc.get("traceEvents") or []:
            if not isinstance(ev, dict) or "ts" not in ev:
                continue
            out = dict(ev)
            out["ts"] = int(ev["ts"]) + shift_us
            out["pid"] = pid
            if attempt > 1:
                out["args"] = {**(out.get("args") or {}), "attempt": attempt}
            events.append(out)

    events.sort(key=lambda e: (e.get("ph") != "M", e.get("pid", 0),
                               e.get("ts", 0)))
    merged_from = {f"r{r}.a{a}": os.path.basename(paths[(r, a)])
                   for (r, a) in sorted(docs)}
    for (replica, attempt, gen) in sorted(req_docs):
        tag = f"serve:{replica}.a{attempt}"
        if gen != 2**31:
            tag += f".g{gen}"
        merged_from[tag] = os.path.basename(
            req_paths[(replica, attempt, gen)])
    salvaged = sorted(
        [f"r{r}.a{a}" for (r, a), d in docs.items() if d.get("_salvaged")]
        + [f"serve:{rep}.a{a}" + (f".g{g}" if g != 2**31 else "")
           for (rep, a, g), d in req_docs.items() if d.get("_salvaged")])
    return {
        "otherData": {
            "schema_version": fleetobs.SCHEMA_VERSION,
            "run_ids": run_ids,
            "merged_from": merged_from,
            "track_groups": {
                **{f"{h}/rank{r}": pid
                   for (h, r), pid in pid_by_group.items()},
                **serve_pids,
            },
            "salvaged": salvaged,
            "dropped_spans": dropped_spans,
            "origin_wall": origin,
        },
        "displayTimeUnit": "ms",
        "traceEvents": events,
    }


def collect_goodput(directory: str) -> dict[int, dict]:
    """Final (highest-attempt) cumulative goodput per rank."""
    best: dict[int, tuple[int, str]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        names = []
    for name in names:
        m = _GOODPUT_RE.fullmatch(name)
        if m:
            rank, attempt = int(m.group(1)), int(m.group(2))
            if rank not in best or attempt > best[rank][0]:
                best[rank] = (attempt, os.path.join(directory, name))
    out: dict[int, dict] = {}
    for rank, (_, path) in sorted(best.items()):
        try:
            with open(path) as fh:
                out[rank] = json.load(fh)
        except (OSError, ValueError):
            print(f"trace_merge: unreadable {path} — skipped",
                  file=sys.stderr)
    if 0 not in out:  # legacy plain file covers rank 0
        plain = os.path.join(directory, "goodput.json")
        try:
            with open(plain) as fh:
                out[0] = json.load(fh)
        except (OSError, ValueError):
            pass
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank telemetry into one fleet trace/goodput")
    ap.add_argument("directory", help="checkpoint/log dir with the artifacts")
    ap.add_argument("--out-dir", default=None,
                    help="where to write outputs (default: the input dir)")
    ap.add_argument("--straggler-threshold", type=float, default=2.0,
                    help="flag steps slower than this multiple of the "
                         "fleet-typical step time (default 2.0)")
    ap.add_argument("--allow-mixed-run", action="store_true",
                    help="merge artifacts even when run ids differ")
    args = ap.parse_args(argv)
    out_dir = args.out_dir or args.directory
    os.makedirs(out_dir, exist_ok=True)

    merged = merge_traces(args.directory,
                          allow_mixed_run=args.allow_mixed_run)
    trace_path = os.path.join(out_dir, MERGED_TRACE)
    with open(trace_path, "w") as fh:
        json.dump(merged, fh)
    groups = merged["otherData"]["track_groups"]
    salvaged = merged["otherData"]["salvaged"]
    print(f"trace_merge: {trace_path} — {len(merged['traceEvents'])} events, "
          f"{len(groups)} track group(s)"
          + (f", salvaged {salvaged}" if salvaged else ""))
    dropped = merged["otherData"].get("dropped_spans", 0)
    if dropped:
        print(f"trace_merge: WARNING — {dropped} request span(s) were "
              f"dropped at capture (ring buffer full); raise the trace "
              f"event capacity", file=sys.stderr)

    per_rank = collect_goodput(args.directory)
    if per_rank:
        fleet = fleetobs.aggregate_goodput(per_rank)
        if len(fleet.get("run_ids") or []) > 1 and not args.allow_mixed_run:
            raise SystemExit(
                f"trace_merge: goodput artifacts span runs "
                f"{fleet['run_ids']} — refusing (--allow-mixed-run to "
                f"override)")
        gp_path = os.path.join(out_dir, FLEET_GOODPUT)
        fleetobs.write_json_atomic(gp_path, fleet)
        print(f"trace_merge: {gp_path} — ranks {fleet['ranks']}, "
              f"goodput {fleet['goodput_fraction']:.1%}, "
              f"coverage {fleet['coverage']:.1%}, "
              f"attempts {fleet['attempts']}")

    rows_by_rank = fleetobs.load_steprows(args.directory)
    if rows_by_rank:
        rows = fleetobs.detect_stragglers(
            rows_by_rank, threshold=args.straggler_threshold)
        sg_path = fleetobs.write_stragglers(out_dir, rows)
        flagged = [r for r in rows if r["flagged"]]
        print(f"trace_merge: {sg_path} — {len(rows)} step(s) compared, "
              f"{len(flagged)} flagged"
              + (f" (worst: step {max(flagged, key=lambda r: r['delta_s'])['step']}"
                 f" rank {max(flagged, key=lambda r: r['delta_s'])['slowest_rank']}"
                 f" +{max(flagged, key=lambda r: r['delta_s'])['delta_s']:.3f}s)"
                 if flagged else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
