#!/usr/bin/env python
"""ViT-B/16 perf rows (VERDICT r2 #3 — BASELINE.json configs[2], previously
correctness-only). Runs the standard bench train-step harness at a small
per-chip batch sweep and records BENCH_VIT.json.

ViT-B/16 at 224px has 197 tokens/image — below the ~1024-token threshold
where the padded Pallas path pays (measured r3 AND re-measured r4 against
the clean no-dropout baseline: 68.1 vs 63.4 ms/step), so ``auto``
dispatches the fused XLA attention. Rows sweep per-chip batch; dropout is
0.0 (torchvision factory parity — the r3 rows benchmarked a harder model,
see PROFILE_VIT.md).

    python benchmarks/vit_bench.py [--out BENCH_VIT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="BENCH_VIT.json")
    p.add_argument("--batches", default="64,128,256")
    args = p.parse_args(argv)

    import jax

    from bench import bench

    rows = []
    for b in [int(x) for x in args.batches.split(",")]:
        t0 = time.perf_counter()
        try:
            r = bench("vit_b16", per_chip_batch=b, steps=100, warmup=4,
                      precision="bf16", quiet=True)
            rows.append({"per_chip_batch": b, "value": r["value"],
                         "unit": r["unit"], "mfu": r["extra"]["mfu"],
                         "step_ms": r["extra"]["step_ms"],
                         "roofline": r["extra"].get("roofline", {}),
                         "wall_s": round(time.perf_counter() - t0, 1),
                         "ok": True})
        except Exception as e:
            msg = str(e)
            rows.append({"per_chip_batch": b, "ok": False,
                         "error": ("OOM" if "RESOURCE_EXHAUSTED" in msg
                                   else msg[:200])})
        print(json.dumps(rows[-1]), file=sys.stderr, flush=True)
    ok = [r for r in rows if r["ok"]]
    best = max(ok, key=lambda r: r["mfu"]) if ok else None
    out = {"metric": "vit_b16_imagenet_train_throughput",
           "device": jax.devices()[0].device_kind,
           "best": best, "rows": rows}
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"best": best, "out": args.out}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
