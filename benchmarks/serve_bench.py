#!/usr/bin/env python
"""Serving benchmark: continuous batching under open-loop Poisson load.

Chipless by design — the whole pipeline (paged KV cache, bucketed AOT
prefill/decode, admission/eviction) runs on CPU exactly as it would on a
TPU pod, so this doubles as the end-to-end CI leg. Two measured phases:

- ``batch1``: closed-loop, one request at a time — the interactive
  latency floor (tokens/sec/chip at batch 1).
- ``saturation``: the full request set under the open-loop arrival
  schedule (``--rate`` req/s Poisson, or everything at t=0 when 0) — the
  throughput ceiling plus honest p50/p99 TTFT and inter-token latency,
  because an open loop keeps arriving while the engine is saturated.

``--aot`` emits the chipless byte/FLOP model of the decode step instead:
the same ``jit(...).lower(abstract).compile()`` front-end as
profile_step.py, with per-region HBM bytes attributed by the serve_*
named-scope tags (serve_cache / serve_attn / serve_mlp / serve_moe /
serve_head) and gated in CI by ``check_regression.py --aot-bytes``
against the ``aot_regions`` golden (key
``<model>_decode b<bucket> s<max_len> -``).

``--spec-decode ngram|draft`` (r19) runs saturation a second time with
speculative decoding ON over the same seeded stream, asserts greedy
token identity request-by-request, and reports the acceptance rate,
accepted-length histogram, and a modeled tokens/sec multiplier: mean
tokens emitted per verify step times the decode/verify byte ratio from
the AOT census (verify golden key ``<model>_verify b<bucket> s<K+1> -``).

Human-readable progress goes to stderr; the result JSON to stdout
(pipeable into check_regression.py, like bench.py).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

#: Named-scope tags the decode forward emits (models/llama.py decode path).
SERVE_TAG_RE = re.compile(r"\bserve_(embed|cache|attn|mlp|moe|head)\b")


def _say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_serving(model_name: str, *, page_size: int, num_pages: int,
                  max_model_len: int, precision: str = "fp32", seed: int = 0):
    """Model + initialized params + cache geometry for serving."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.serve import engine as engine_lib

    dtype = jnp.float32 if precision == "fp32" else jnp.bfloat16
    bundle = registry.create_model(model_name, seq_len=max_model_len,
                                   dtype=dtype, param_dtype=dtype)
    module = bundle.module
    params = module.init(jax.random.PRNGKey(seed),
                         jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    spec = engine_lib.spec_for_module(module, num_pages=num_pages,
                                      page_size=page_size)
    return module, params, spec


def _pct_ms(xs, q) -> float | None:
    return round(float(np.percentile(np.asarray(xs), q)) * 1e3, 3) if xs \
        else None


def latency_summary(done, wall_s: float, num_chips: int) -> dict:
    tokens = sum(len(r.generated) for r in done)
    ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
    itls = [d for r in done for d in r.inter_token_s()]
    tps = tokens / max(wall_s, 1e-9)
    return {
        "requests": len(done),
        "tokens_generated": tokens,
        "wall_s": round(wall_s, 4),
        "tokens_per_s": round(tps, 2),
        "tokens_per_s_per_chip": round(tps / max(num_chips, 1), 2),
        "ttft_ms": {"p50": _pct_ms(ttfts, 50), "p99": _pct_ms(ttfts, 99)},
        "inter_token_ms": {"p50": _pct_ms(itls, 50), "p99": _pct_ms(itls, 99)},
    }


def _make_proposer(args):
    """Fresh proposer per engine — draft proposers own a paged cache pool,
    so replicas must not share one. "ngram" is resolved by the engine;
    "draft" builds the registry model named by --draft-model (default: the
    target model itself with the same init seed — the self-draft acceptance
    ceiling, useful for exercising the full verify/rollback path)."""
    if args.spec_decode == "ngram":
        return "ngram"
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.serve import spec_decode

    dtype = jnp.float32 if args.precision == "fp32" else jnp.bfloat16
    name = args.draft_model or args.model
    bundle = registry.create_model(name, seq_len=args.max_model_len,
                                   dtype=dtype, param_dtype=dtype)
    dparams = bundle.module.init(jax.random.PRNGKey(args.seed),
                                 jnp.zeros((1, 8), jnp.int32),
                                 train=False)["params"]
    return spec_decode.DraftModelProposer(bundle.module, dparams,
                                          draft_len=args.draft_len)


def _build_engine(module, params, spec, args, *, closed_loop: bool,
                  cached: bool, spec_on: bool = False, telemetry=None,
                  metrics=None, reqtrace=None, slo=None):
    from pytorch_distributed_training_example_tpu.serve import engine as engine_lib

    kw = dict(decode_buckets=(1,) if closed_loop else args.decode_buckets,
              prompt_buckets=args.prompt_buckets,
              max_model_len=args.max_model_len, telemetry=telemetry,
              metrics=metrics, reqtrace=reqtrace, slo=slo)
    mk = lambda **extra: engine_lib.ContinuousBatchingEngine(
        module, params, spec, **kw, **extra)
    spec_kw = (dict(spec_decode=_make_proposer(args),
                    draft_len=args.draft_len) if spec_on else {})
    if args.disaggregate:
        return engine_lib.DisaggregatedServe(
            mk(role="prefill", prefix_cache=cached,
               prefill_chunk=args.prefill_chunk),
            mk(role="decode", **spec_kw))
    return mk(prefix_cache=cached, prefill_chunk=args.prefill_chunk,
              **spec_kw)


def _parse_chaos(text: str | None) -> tuple[str, int] | None:
    """``sigterm@completed=K`` / ``kill@completed=K``: drain or hard-kill
    the second replica once K requests have completed fleet-wide."""
    if not text:
        return None
    mode, _, trigger = text.partition("@")
    if mode not in ("sigterm", "kill") or \
            not trigger.startswith("completed="):
        raise SystemExit(f"bad --chaos-replica {text!r} "
                         f"(want sigterm@completed=K or kill@completed=K)")
    return mode, int(trigger.split("=", 1)[1])


def run_phase(module, params, spec, args, requests, *, closed_loop: bool,
              cached: bool = False, spec_on: bool = False, telemetry=None,
              metrics=None, slo=None,
              reqtrace_factory=None) -> tuple[dict, list]:
    """One measured phase; returns (summary dict, completed Requests).

    ``slo`` (an SLOTracker) and ``reqtrace_factory`` (replica name ->
    RequestTrace) instrument the phase's engines with r20 request-level
    observability — a disaggregated pair shares its replica's tracer."""
    from pytorch_distributed_training_example_tpu.serve import loadgen

    submitted = len(requests)
    replicas = 1 if closed_loop else args.replicas
    chaos = None if closed_loop else _parse_chaos(args.chaos_replica)
    rt_for = reqtrace_factory or (lambda name: None)
    if replicas > 1:
        from pytorch_distributed_training_example_tpu.serve import (
            router as router_lib)

        fleet = {f"replica{i}": _build_engine(
                     module, params, spec, args, closed_loop=closed_loop,
                     cached=cached, spec_on=spec_on, telemetry=telemetry,
                     metrics=metrics, reqtrace=rt_for(f"replica{i}"),
                     slo=slo)
                 for i in range(replicas)}
        n_exec = sum(rep.warmup() for rep in fleet.values())
        eng = router_lib.PrefixAffinityRouter(
            fleet, page_size=args.page_size, policy=args.route)
    else:
        eng = _build_engine(module, params, spec, args,
                            closed_loop=closed_loop, cached=cached,
                            spec_on=spec_on, telemetry=telemetry,
                            metrics=metrics, reqtrace=rt_for("replica0"),
                            slo=slo)
        n_exec = eng.warmup()
    chaos_fired = False
    t0 = time.perf_counter()
    if closed_loop:
        for req in requests:
            eng.submit(req)
            eng.run()
    else:
        driver = loadgen.OpenLoopDriver(requests)
        while driver.remaining or eng.has_work:
            driver.pump(eng, time.perf_counter() - t0)
            if chaos and not chaos_fired \
                    and len(eng.completed) >= chaos[1]:
                chaos_fired = True
                target = "replica1"
                _say(f"serve_bench: chaos {chaos[0]} -> {target} "
                     f"(completed={len(eng.completed)})")
                if chaos[0] == "sigterm":
                    eng.drain(target)
                else:
                    eng.kill(target)
            if eng.has_work:
                eng.step()
            else:
                time.sleep(0.0005)  # idle until the next scheduled arrival
    wall = time.perf_counter() - t0
    import jax

    done = eng.completed
    out = latency_summary(done, wall, jax.device_count())
    stats = eng.stats if replicas == 1 else None
    if stats is None:
        stats = {}
        for rep in fleet.values():
            for k, v in rep.stats.items():
                stats[k] = stats.get(k, 0) + v
    out.update(submitted=submitted, executables=n_exec,
               compiles=stats["compiles"], decode_steps=stats["decode_steps"],
               evictions=stats["evictions"])
    assert stats["compiles"] == n_exec, \
        f"steady-state recompile: {stats['compiles']} > {n_exec}"
    assert len(done) == submitted, \
        f"dropped requests: completed {len(done)} of {submitted}"
    if cached:
        out["prefix"] = {
            "hit_rate": round(stats["cached_tokens"]
                              / max(stats["prompt_tokens"], 1), 4),
            "cached_tokens": stats["cached_tokens"],
            "prompt_tokens": stats["prompt_tokens"],
            "cow_copies": stats["cow_copies"],
        }
    if spec_on:
        drafted = stats.get("draft_tokens", 0)
        out["spec"] = {
            "spec_steps": stats.get("spec_steps", 0),
            "draft_tokens": drafted,
            "accepted_tokens": stats.get("accepted_tokens", 0),
            "accept_rate": round(stats.get("accepted_tokens", 0)
                                 / max(drafted, 1), 4),
            "accepted_len_hist": {
                str(n): stats.get(f"spec_accept_{n}", 0)
                for n in range(args.draft_len + 1)},
        }
    if args.disaggregate:
        out["handoffs"] = stats.get("handoffs_out", 0)
    if replicas > 1:
        out["router"] = dict(eng.stats)
        out["router"]["per_replica_completed"] = {
            name: len(rep.completed) for name, rep in fleet.items()}
        out["chaos_fired"] = chaos_fired
    return out, done


def aot_decode_report(model_name: str, *, batch: int, page_size: int,
                      num_pages: int, max_model_len: int,
                      precision: str = "fp32") -> dict:
    """Chipless AOT byte/FLOP model of ONE decode step at one batch bucket.

    Same scheme as profile_step.aot_report: lower the exact engine decode
    program with abstract inputs, tabulate modeled HBM bytes per serve_*
    named-scope region with proportional fusion attribution, and stamp the
    lowering backend so goldens never compare across backends."""
    import collections

    import jax
    import jax.numpy as jnp

    import profile_step

    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.serve.kv_cache import (
        pages_for_tokens)

    dtype = jnp.float32 if precision == "fp32" else jnp.bfloat16
    bundle = registry.create_model(model_name, seq_len=max_model_len,
                                   dtype=dtype, param_dtype=dtype)
    module = bundle.module
    table_width = pages_for_tokens(max_model_len, page_size)
    sds = jax.ShapeDtypeStruct
    tok = sds((batch, 1), jnp.int32)
    pos = sds((batch, 1), jnp.int32)
    table = sds((batch, table_width), jnp.int32)
    last = sds((batch,), jnp.int32)

    def ctx(positions, page_table, last_index):
        return dict(positions=positions, page_table=page_table,
                    cache_spec=(num_pages, page_size),
                    last_index=last_index, attn_impl="auto")

    def init_fn(rng, tokens, positions, page_table, last_index):
        return module.init(rng, tokens, train=False,
                           decode_ctx=ctx(positions, page_table, last_index))

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0), tok, pos, table,
                            last)
    params_abs, cache_abs = shapes["params"], shapes["cache"]

    def run(params, cache, tokens, positions, page_table, last_index):
        logits, vs = module.apply(
            {"params": params, "cache": cache}, tokens, train=False,
            decode_ctx=ctx(positions, page_table, last_index),
            mutable=["cache"])
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), vs["cache"]

    compiled = jax.jit(run, donate_argnums=1).lower(
        params_abs, cache_abs, tok, pos, table, last).compile()
    regions, ca = _tabulate_regions(compiled)
    return {
        "mode": "aot_hlo_model",
        "attribution": "proportional_bytes",
        "backend_lowering": jax.default_backend(),
        "model": f"{model_name}_decode",
        "per_chip_batch": batch,
        "seq_len": max_model_len,       # KV capacity: the decode shape knob
        "page_size": page_size,
        "num_pages": num_pages,
        "precision": precision,
        "xla_flops_per_step": ca.get("flops"),
        "xla_bytes_accessed": ca.get("bytes accessed"),
        "regions": regions,
    }


def _tabulate_regions(compiled) -> tuple[dict, dict]:
    """Per-region modeled HBM bytes for one compiled serve program (the
    profile_step scheme with serve_* named-scope tags)."""
    import collections

    import profile_step

    hlo_text = compiled.as_text()
    op_cat, _ = profile_step.build_op_categories(hlo_text)
    op_bytes = profile_step.build_op_bytes(hlo_text)
    op_tag = profile_step.build_op_moe_tags(hlo_text, tag_re=SERVE_TAG_RE)
    op_w = profile_step.build_op_moe_weights(hlo_text, tag_re=SERVE_TAG_RE)
    op_interior = profile_step.build_pallas_interior(hlo_text)

    regions: dict[str, dict] = {}

    def row(tag):
        return regions.setdefault(tag, {"ops": 0, "gbytes_modeled": 0.0,
                                        "by_category": collections.Counter()})

    for op, b in op_bytes.items():
        if op in op_interior:
            continue
        assigned = 0.0
        for tag, frac in op_w.get(op, {}).items():
            row(tag)["gbytes_modeled"] += b * frac / 1e9
            assigned += frac
        if assigned < 1.0:
            row("other")["gbytes_modeled"] += b * (1.0 - assigned) / 1e9
        r = row(op_tag.get(op, "other"))
        r["ops"] += 1
        if b or op_cat.get(op) not in (None, "copy_layout"):
            r["by_category"][op_cat.get(op, "?")] += 1
    for r in regions.values():
        r["gbytes_modeled"] = round(r["gbytes_modeled"], 4)
        r["by_category"] = dict(r["by_category"].most_common(6))
    try:
        ca = compiled.cost_analysis() or {}
    except Exception:
        ca = {}
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return (dict(sorted(regions.items(),
                        key=lambda kv: -kv[1]["gbytes_modeled"])), ca)


def aot_prefill_report(model_name: str, *, prompt_bucket: int, page_size: int,
                       num_pages: int, max_model_len: int,
                       precision: str = "fp32") -> dict:
    """Chipless AOT byte model of ONE batch-1 prefill program at one prompt
    bucket — the unit of work a prefix-cache hit AVOIDS. The cached-run
    summary converts (report gbytes / bucket) into per-token prefill cost
    to model prefill-bytes-avoided; CI gates the census through the same
    ``check_regression.py --aot-bytes`` golden as the decode rows (key
    ``<model>_prefill b1 s<bucket> -``)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.serve.kv_cache import (
        pages_for_tokens)

    dtype = jnp.float32 if precision == "fp32" else jnp.bfloat16
    bundle = registry.create_model(model_name, seq_len=max_model_len,
                                   dtype=dtype, param_dtype=dtype)
    module = bundle.module
    table_width = pages_for_tokens(max_model_len, page_size)
    sds = jax.ShapeDtypeStruct
    tok = sds((1, prompt_bucket), jnp.int32)
    pos = sds((1, prompt_bucket), jnp.int32)
    table = sds((1, table_width), jnp.int32)
    last = sds((1,), jnp.int32)

    def ctx(positions, page_table, last_index):
        return dict(positions=positions, page_table=page_table,
                    cache_spec=(num_pages, page_size),
                    last_index=last_index, attn_impl="auto")

    def init_fn(rng, tokens, positions, page_table, last_index):
        return module.init(rng, tokens, train=False,
                           decode_ctx=ctx(positions, page_table, last_index))

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0), tok, pos, table,
                            last)
    params_abs, cache_abs = shapes["params"], shapes["cache"]

    def run(params, cache, tokens, positions, page_table, last_index):
        logits, vs = module.apply(
            {"params": params, "cache": cache}, tokens, train=False,
            decode_ctx=ctx(positions, page_table, last_index),
            mutable=["cache"])
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), vs["cache"]

    compiled = jax.jit(run, donate_argnums=1).lower(
        params_abs, cache_abs, tok, pos, table, last).compile()
    regions, ca = _tabulate_regions(compiled)
    return {
        "mode": "aot_hlo_model",
        "attribution": "proportional_bytes",
        "backend_lowering": jax.default_backend(),
        "model": f"{model_name}_prefill",
        "per_chip_batch": 1,
        "seq_len": prompt_bucket,       # the prefill window: its shape knob
        "page_size": page_size,
        "num_pages": num_pages,
        "precision": precision,
        "xla_flops_per_step": ca.get("flops"),
        "xla_bytes_accessed": ca.get("bytes accessed"),
        "regions": regions,
    }


def aot_verify_report(model_name: str, *, batch: int, width: int,
                      page_size: int, num_pages: int, max_model_len: int,
                      precision: str = "fp32") -> dict:
    """Chipless AOT byte model of ONE speculative VERIFY step.

    The verify program is the engine's multi-token history-attention
    forward with ``all_logits`` — it scores all ``width = draft_len + 1``
    positions in one pass and returns the per-position argmax stacked with
    the echoed input tokens (the engine's one-fetch acceptance contract).
    Lowered here exactly as ``_get_step("verify", batch, width)`` lowers
    it, so the byte census is the program serving actually runs. CI gates
    it through the same ``check_regression.py --aot-bytes`` golden as the
    decode rows (key ``<model>_verify b<batch> s<width> -``); the spec
    summary divides decode bytes by verify bytes to model the tokens/sec
    multiplier (verify reads the weights once for up to ``width`` emitted
    tokens — that amortization IS the speedup)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.serve.kv_cache import (
        pages_for_tokens)

    dtype = jnp.float32 if precision == "fp32" else jnp.bfloat16
    bundle = registry.create_model(model_name, seq_len=max_model_len,
                                   dtype=dtype, param_dtype=dtype)
    module = bundle.module
    table_width = pages_for_tokens(max_model_len, page_size)
    sds = jax.ShapeDtypeStruct
    tok = sds((batch, width), jnp.int32)
    pos = sds((batch, width), jnp.int32)
    table = sds((batch, table_width), jnp.int32)
    last = sds((batch,), jnp.int32)

    def ctx(positions, page_table, last_index):
        return dict(positions=positions, page_table=page_table,
                    cache_spec=(num_pages, page_size),
                    last_index=last_index, history=True, all_logits=True,
                    attn_impl="auto")

    def init_fn(rng, tokens, positions, page_table, last_index):
        return module.init(rng, tokens, train=False,
                           decode_ctx=ctx(positions, page_table, last_index))

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0), tok, pos, table,
                            last)
    params_abs, cache_abs = shapes["params"], shapes["cache"]

    def run(params, cache, tokens, positions, page_table, last_index):
        logits, vs = module.apply(
            {"params": params, "cache": cache}, tokens, train=False,
            decode_ctx=ctx(positions, page_table, last_index),
            mutable=["cache"])
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jnp.stack([out, tokens.astype(jnp.int32)], axis=1), \
            vs["cache"]

    compiled = jax.jit(run, donate_argnums=1).lower(
        params_abs, cache_abs, tok, pos, table, last).compile()
    regions, ca = _tabulate_regions(compiled)
    return {
        "mode": "aot_hlo_model",
        "attribution": "proportional_bytes",
        "backend_lowering": jax.default_backend(),
        "model": f"{model_name}_verify",
        "per_chip_batch": batch,
        "seq_len": width,               # verify window: draft_len + 1
        "max_model_len": max_model_len,
        "page_size": page_size,
        "num_pages": num_pages,
        "precision": precision,
        "xla_flops_per_step": ca.get("flops"),
        "xla_bytes_accessed": ca.get("bytes accessed"),
        "regions": regions,
    }


def _report_gbytes(report: dict) -> float:
    return sum(r["gbytes_modeled"] for r in report["regions"].values())


def _int_tuple(text: str) -> tuple[int, ...]:
    return tuple(int(t) for t in text.split(",") if t)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama_tiny")
    p.add_argument("--precision", default="fp32", choices=("fp32", "bf16"))
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--num-pages", type=int, default=128)
    p.add_argument("--max-model-len", type=int, default=128)
    p.add_argument("--decode-buckets", type=_int_tuple, default=(1, 2, 4, 8))
    p.add_argument("--prompt-buckets", type=_int_tuple, default=(16, 32))
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--rate", type=float, default=0.0,
                   help="open-loop Poisson arrivals per second; 0 = all "
                        "requests arrive at t=0 (saturation)")
    p.add_argument("--prompt-len", default="4:24", help="min:max prompt len")
    p.add_argument("--max-new", default="4:24", help="min:max new tokens")
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--skip-batch1", action="store_true")
    p.add_argument("--templates", type=int, default=0,
                   help="shared-prefix prompt templates (Zipf-popular); "
                        "0 = fully random prompts")
    p.add_argument("--zipf-a", type=float, default=1.2,
                   help="Zipf exponent for template popularity")
    p.add_argument("--prefix-len", default="16:16",
                   help="min:max template prefix length in tokens")
    p.add_argument("--prefix-cache", action="store_true",
                   help="run saturation twice (uncached baseline, then "
                        "prefix cache ON), verify token identity, report "
                        "hit rate + TTFT/ITL deltas + modeled "
                        "prefill-bytes-avoided")
    p.add_argument("--spec-decode", default="off",
                   choices=("off", "ngram", "draft"),
                   help="run saturation again with speculative decoding ON "
                        "(same seeded stream), assert greedy token "
                        "identity, report acceptance rate + accepted-length "
                        "histogram + modeled tokens/s multiplier from the "
                        "AOT byte census")
    p.add_argument("--draft-len", type=int, default=4,
                   help="speculation window: tokens drafted per slot-step")
    p.add_argument("--draft-model", default=None,
                   help="with --spec-decode draft: registry model name for "
                        "the draft proposer (default: the target model "
                        "itself — self-draft acceptance ceiling)")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="chunked-prefill window (tokens, multiple of the "
                        "page size); 0 = whole prompt")
    p.add_argument("--disaggregate", action="store_true",
                   help="prefill-role + decode-role engine pair per replica")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve replicas behind the prefix-affinity router")
    p.add_argument("--route", default="affinity",
                   choices=("affinity", "least_loaded"))
    p.add_argument("--chaos-replica", default=None,
                   help="sigterm@completed=K (drain) or kill@completed=K "
                        "(hard loss + re-route) against replica1 during "
                        "saturation; needs --replicas >= 2")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="start a fleetobs MetricsServer (0 = ephemeral) and "
                        "export pdtx_serve_* gauges")
    p.add_argument("--trace-dir", default=None,
                   help="write trace_events.json/goodput.json here")
    p.add_argument("--slo", action="store_true",
                   help="instrument the saturation phase with per-request "
                        "span tracing + sliding-window TTFT/ITL quantiles "
                        "(serve/slo.py); artifacts go to --slo-dir")
    p.add_argument("--slo-dir", default=None,
                   help="write slo.jsonl + reqtrace.*.json here "
                        "(default: --trace-dir)")
    p.add_argument("--slo-window", type=int, default=256,
                   help="sliding-window size in samples per replica/role")
    p.add_argument("--slo-ttft-ms", type=float, default=0.0,
                   help="TTFT SLO target in ms (0 = quantiles only)")
    p.add_argument("--slo-itl-ms", type=float, default=0.0,
                   help="inter-token-latency SLO target in ms (0 = "
                        "quantiles only)")
    p.add_argument("--trace-events", type=int, default=4096,
                   help="request-span ring capacity per replica")
    p.add_argument("--trace-overhead", action="store_true",
                   help="with --slo: run saturation once untraced first, "
                        "assert greedy token identity traced vs untraced, "
                        "and report host-side tracing overhead in µs per "
                        "decode step")
    p.add_argument("--aot", action="store_true",
                   help="emit the chipless AOT decode-step byte model "
                        "instead of running load")
    p.add_argument("--aot-bucket", type=int, default=None,
                   help="with --aot: single-bucket report JSON on stdout "
                        "(pipe into check_regression.py --aot-bytes)")
    p.add_argument("--aot-prefill-bucket", type=int, default=None,
                   help="with --aot: single batch-1 PREFILL report at this "
                        "prompt bucket on stdout (pipe into "
                        "check_regression.py --aot-bytes)")
    p.add_argument("--aot-verify-bucket", type=int, default=None,
                   help="with --aot: single speculative VERIFY report at "
                        "this decode bucket (width --draft-len + 1) on "
                        "stdout (pipe into check_regression.py --aot-bytes)")
    p.add_argument("--json", default=None, help="also write result JSON here")
    args = p.parse_args(argv)

    result: dict = {"mode": "serve_bench", "model": args.model,
                    "page_size": args.page_size, "num_pages": args.num_pages,
                    "max_model_len": args.max_model_len,
                    "decode_buckets": list(args.decode_buckets),
                    "prompt_buckets": list(args.prompt_buckets),
                    "seed": args.seed}

    if args.aot:
        if args.aot_verify_bucket:
            _say(f"serve_bench: AOT verify model, bucket "
                 f"{args.aot_verify_bucket}, width {args.draft_len + 1}")
            print(json.dumps(aot_verify_report(
                args.model, batch=args.aot_verify_bucket,
                width=args.draft_len + 1, page_size=args.page_size,
                num_pages=args.num_pages, max_model_len=args.max_model_len,
                precision=args.precision), indent=2))
            return 0
        if args.aot_prefill_bucket:
            _say(f"serve_bench: AOT prefill model, "
                 f"bucket {args.aot_prefill_bucket}")
            print(json.dumps(aot_prefill_report(
                args.model, prompt_bucket=args.aot_prefill_bucket,
                page_size=args.page_size, num_pages=args.num_pages,
                max_model_len=args.max_model_len,
                precision=args.precision), indent=2))
            return 0
        buckets = ([args.aot_bucket] if args.aot_bucket
                   else list(args.decode_buckets))
        reports = []
        for b in buckets:
            _say(f"serve_bench: AOT decode model, bucket {b}")
            reports.append(aot_decode_report(
                args.model, batch=b, page_size=args.page_size,
                num_pages=args.num_pages, max_model_len=args.max_model_len,
                precision=args.precision))
        if args.aot_bucket:
            print(json.dumps(reports[0], indent=2))
            return 0
        for sp in args.prompt_buckets:
            _say(f"serve_bench: AOT prefill model, bucket {sp}")
            reports.append(aot_prefill_report(
                args.model, prompt_bucket=sp, page_size=args.page_size,
                num_pages=args.num_pages, max_model_len=args.max_model_len,
                precision=args.precision))
        if args.spec_decode != "off":
            for b in buckets:
                _say(f"serve_bench: AOT verify model, bucket {b}, "
                     f"width {args.draft_len + 1}")
                reports.append(aot_verify_report(
                    args.model, batch=b, width=args.draft_len + 1,
                    page_size=args.page_size, num_pages=args.num_pages,
                    max_model_len=args.max_model_len,
                    precision=args.precision))
        result["aot"] = reports
        print(json.dumps(result, indent=2))
        if args.json:
            with open(args.json, "w") as fh:
                json.dump(result, fh, indent=2)
        return 0

    from pytorch_distributed_training_example_tpu.serve import loadgen
    from pytorch_distributed_training_example_tpu.utils import telemetry as tele

    pl_min, pl_max = (int(t) for t in args.prompt_len.split(":"))
    mn_min, mn_max = (int(t) for t in args.max_new.split(":"))
    pfx_min, pfx_max = (int(t) for t in args.prefix_len.split(":"))
    module, params, spec = build_serving(
        args.model, page_size=args.page_size, num_pages=args.num_pages,
        max_model_len=args.max_model_len, precision=args.precision,
        seed=args.seed)
    vocab = int(module.vocab_size)
    if args.templates and pl_max + pfx_max > max(args.prompt_buckets):
        raise SystemExit(
            f"--templates: prefix {pfx_max} + prompt {pl_max} exceeds the "
            f"largest prompt bucket {max(args.prompt_buckets)}")
    mkload = lambda rate, n, seed: loadgen.generate_requests(loadgen.LoadSpec(
        num_requests=n, rate=rate, prompt_len_min=pl_min,
        prompt_len_max=pl_max, max_new_min=mn_min, max_new_max=mn_max,
        vocab_size=vocab, eos_id=args.eos_id, seed=seed,
        num_templates=args.templates, zipf_a=args.zipf_a,
        prefix_len_min=pfx_min, prefix_len_max=pfx_max))

    metrics = None
    if args.metrics_port is not None:
        from pytorch_distributed_training_example_tpu.utils import fleetobs

        metrics = fleetobs.MetricsServer(port=args.metrics_port).start()
        _say(f"serve_bench: /metrics on port {metrics.port}")
        result["metrics_port"] = metrics.port
    recorder = tele.SpanRecorder(run_id=f"serve_bench_s{args.seed}")

    # r20 SLO kit: one tracker for the bench, one request-trace ring per
    # replica of the saturation phase. The run id matches the SpanRecorder
    # stamp so trace_merge accepts both artifact families as one run.
    slo_tracker = None
    tracers: dict = {}
    reqtrace_factory = None
    if args.slo:
        from pytorch_distributed_training_example_tpu.serve import (
            slo as slo_lib)

        slo_tracker = slo_lib.SLOTracker(
            window=args.slo_window, ttft_target_ms=args.slo_ttft_ms,
            itl_target_ms=args.slo_itl_ms)

        def reqtrace_factory(name):
            rt = slo_lib.RequestTrace(
                name, run_id=f"serve_bench_s{args.seed}",
                capacity=args.trace_events)
            tracers[name] = rt
            return rt
    elif args.trace_overhead:
        raise SystemExit("--trace-overhead needs --slo")

    if not args.skip_batch1:
        _say("serve_bench: phase batch1 (closed loop)")
        result["batch1"], _ = run_phase(
            module, params, spec, args, mkload(0.0, min(args.requests, 8),
                                               args.seed + 1),
            closed_loop=True, telemetry=recorder, metrics=metrics)
        _say(f"  batch1: {result['batch1']['tokens_per_s_per_chip']} tok/s/chip")
    if args.trace_overhead:
        # Baseline for the zero-intrusion contract: the same seeded
        # stream, tracing OFF. Greedy decode is deterministic per request
        # regardless of batching interleave, so the traced run below must
        # reproduce these exact tokens.
        _say("serve_bench: phase saturation_untraced (overhead baseline)")
        result["saturation_untraced"], untraced_done = run_phase(
            module, params, spec, args, mkload(args.rate, args.requests,
                                               args.seed),
            closed_loop=False, telemetry=recorder, metrics=metrics)
    _say(f"serve_bench: phase saturation (open loop, rate={args.rate})")
    result["saturation"], base_done = run_phase(
        module, params, spec, args, mkload(args.rate, args.requests,
                                           args.seed),
        closed_loop=False, telemetry=recorder, metrics=metrics,
        slo=slo_tracker, reqtrace_factory=reqtrace_factory)
    sat = result["saturation"]
    if args.trace_overhead:
        untraced_by_id = {r.request_id: r.generated for r in untraced_done}
        for r in base_done:
            assert r.generated == untraced_by_id[r.request_id], \
                f"tracing changed tokens for {r.request_id}"
        ut = result["saturation_untraced"]
        overhead_us = (sat["wall_s"] - ut["wall_s"]) \
            / max(sat["decode_steps"], 1) * 1e6
        result["trace_overhead"] = {
            "token_identity": "ok",
            "untraced_wall_s": ut["wall_s"],
            "traced_wall_s": sat["wall_s"],
            "decode_steps": sat["decode_steps"],
            "overhead_us_per_step": round(overhead_us, 2),
        }
        _say(f"  trace overhead: {result['trace_overhead']}")
    _say(f"  saturation: {sat['tokens_per_s_per_chip']} tok/s/chip, "
         f"ttft p50/p99 {sat['ttft_ms']['p50']}/{sat['ttft_ms']['p99']} ms, "
         f"itl p50/p99 {sat['inter_token_ms']['p50']}"
         f"/{sat['inter_token_ms']['p99']} ms")
    if args.prefix_cache:
        _say("serve_bench: phase saturation_cached (prefix cache ON, "
             "same seeded stream)")
        result["saturation_cached"], cached_done = run_phase(
            module, params, spec, args, mkload(args.rate, args.requests,
                                               args.seed),
            closed_loop=False, cached=True, telemetry=recorder,
            metrics=metrics)
        csat = result["saturation_cached"]
        base_by_id = {r.request_id: r.generated for r in base_done}
        for r in cached_done:
            assert r.generated == base_by_id[r.request_id], \
                f"token identity broken for {r.request_id}"
        prefill_report = aot_prefill_report(
            args.model, prompt_bucket=max(args.prompt_buckets),
            page_size=args.page_size, num_pages=args.num_pages,
            max_model_len=args.max_model_len, precision=args.precision)
        per_tok_gb = _report_gbytes(prefill_report) / max(args.prompt_buckets)
        delta = lambda k, q: (None if sat[k][q] is None or csat[k][q] is None
                              else round(csat[k][q] - sat[k][q], 3))
        result["prefix_cache"] = {
            **csat["prefix"],
            "token_identity": "ok",
            "ttft_ms_delta": {"p50": delta("ttft_ms", "p50"),
                              "p99": delta("ttft_ms", "p99")},
            "inter_token_ms_delta": {
                "p50": delta("inter_token_ms", "p50"),
                "p99": delta("inter_token_ms", "p99")},
            "prefill_gbytes_avoided_modeled": round(
                per_tok_gb * csat["prefix"]["cached_tokens"], 4),
            "prefill_bucket_gbytes_modeled": round(
                _report_gbytes(prefill_report), 4),
        }
        _say(f"  prefix cache: hit {result['prefix_cache']['hit_rate']}, "
             f"ttft p50 delta {result['prefix_cache']['ttft_ms_delta']['p50']}"
             f" ms, modeled prefill GB avoided "
             f"{result['prefix_cache']['prefill_gbytes_avoided_modeled']}")
    if args.spec_decode != "off":
        _say(f"serve_bench: phase saturation_spec ({args.spec_decode}, "
             f"draft_len={args.draft_len}, same seeded stream)")
        result["saturation_spec"], spec_done = run_phase(
            module, params, spec, args, mkload(args.rate, args.requests,
                                               args.seed),
            closed_loop=False, spec_on=True, telemetry=recorder,
            metrics=metrics)
        ssat = result["saturation_spec"]
        base_by_id = {r.request_id: r.generated for r in base_done}
        for r in spec_done:
            assert r.generated == base_by_id[r.request_id], \
                f"spec token identity broken for {r.request_id}"
        # Modeled multiplier: the unsped engine pays one decode step's
        # bytes per emitted token; the sped one pays one verify step's
        # bytes per (mean accepted + 1 bonus) tokens. Draft cost is not
        # in the ratio — zero device work for ngram, and the draft
        # model's census is the plain decode row of --draft-model.
        bucket = max(args.decode_buckets)
        verify_report = aot_verify_report(
            args.model, batch=bucket, width=args.draft_len + 1,
            page_size=args.page_size, num_pages=args.num_pages,
            max_model_len=args.max_model_len, precision=args.precision)
        decode_report = aot_decode_report(
            args.model, batch=bucket, page_size=args.page_size,
            num_pages=args.num_pages, max_model_len=args.max_model_len,
            precision=args.precision)
        hist = ssat["spec"]["accepted_len_hist"]
        slot_steps = sum(hist.values())
        mean_emitted = (ssat["spec"]["accepted_tokens"] + slot_steps) \
            / max(slot_steps, 1)
        vg = _report_gbytes(verify_report)
        dg = _report_gbytes(decode_report)
        result["spec_decode"] = {
            **ssat["spec"],
            "token_identity": "ok",
            "mean_emitted_per_verify": round(mean_emitted, 4),
            "decode_step_gbytes_modeled": round(dg, 4),
            "verify_step_gbytes_modeled": round(vg, 4),
            "modeled_tokens_per_s_multiplier": round(
                mean_emitted * dg / max(vg, 1e-12), 4),
        }
        _say(f"  spec decode: accept rate "
             f"{result['spec_decode']['accept_rate']}, mean emitted/verify "
             f"{result['spec_decode']['mean_emitted_per_verify']}, modeled "
             f"tok/s multiplier "
             f"{result['spec_decode']['modeled_tokens_per_s_multiplier']}")
    result["goodput"] = {k: recorder.goodput()[k]
                         for k in ("goodput_fraction", "coverage", "wall_s",
                                   "categories_s")}
    if args.trace_dir:
        recorder.write(args.trace_dir)
        _say(f"serve_bench: wrote trace/goodput to {args.trace_dir}")
    if slo_tracker is not None:
        dropped = sum(rt.dropped_spans for rt in tracers.values())
        slo_dir = args.slo_dir or args.trace_dir
        if slo_dir:
            run_id = f"serve_bench_s{args.seed}"
            slo_path = slo_tracker.flush(slo_dir, run_id,
                                         dropped_spans=dropped)
            for rt in tracers.values():
                rt.write(slo_dir)
            _say(f"serve_bench: wrote {slo_path} + {len(tracers)} "
                 f"reqtrace file(s)")
        if metrics is not None:
            metrics.update(**slo_tracker.gauges(extra_dropped=dropped))
            metrics.update_histograms(**slo_tracker.histograms())
        result["slo"] = {
            "run_id": f"serve_bench_s{args.seed}",
            "attainment": round(slo_tracker.overall_attainment(), 4),
            "breaches": slo_tracker.breaches,
            "dropped_spans": dropped,
            "windows": slo_tracker.snapshot(),
        }
    if metrics is not None:
        result["metrics_snapshot"] = {
            k: v for k, v in metrics.snapshot().items()
            if k.startswith("serve_")}
        metrics.stop()
    print(json.dumps(result, indent=2))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(result, fh, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
