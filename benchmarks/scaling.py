#!/usr/bin/env python
"""Scaling-efficiency sweep — the driver's second metric (BASELINE.json:
"DDP scaling efficiency v4-8 -> v4-32", target >= 90%).

Runs the bench at increasing data-parallel degree over the available chips
and reports throughput plus efficiency relative to linear scaling from the
smallest size. With one real chip (this CI), ``--fake-devices N`` exercises
the harness on a fake CPU mesh so the sweep logic itself stays tested; on a
pod slice it measures the real ICI gradient-psum overhead.

    python benchmarks/scaling.py                     # all real chips
    python benchmarks/scaling.py --fake-devices 8    # harness check on CPU
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--per-chip-batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--sizes", default=None,
                   help="comma-separated dp sizes (default: powers of 2 up to #chips)")
    p.add_argument("--fake-devices", type=int, default=None)
    args = p.parse_args(argv)

    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={args.fake_devices}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from bench import bench

    n = jax.device_count()
    if args.sizes:
        sizes = [int(s) for s in args.sizes.split(",")]
    else:
        sizes = []
        s = 1
        while s <= n:
            sizes.append(s)
            s *= 2
    rows = []
    for s in sizes:
        r = bench(args.model, args.image_size, args.per_chip_batch,
                  steps=args.steps, quiet=True, seq_len=args.seq_len,
                  mesh_spec={"data": s}, devices=jax.devices()[:s])
        rows.append({"chips": s, "per_chip": r["value"], "unit": r["unit"],
                     "mfu": r["extra"]["mfu"]})
        print(f"# {s} chip(s): {r['value']} {r['unit']}", file=sys.stderr)

    base = rows[0]["per_chip"]
    for row in rows:
        row["scaling_efficiency"] = round(row["per_chip"] / base, 4)
    print(json.dumps({"metric": f"{args.model}_scaling_sweep", "rows": rows}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
