#!/usr/bin/env python
"""Driver benchmark: ResNet-50/ImageNet images/sec/chip + MFU (BASELINE.json metric).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

The reference publishes no numbers (BASELINE.json ``published: {}``), so
``vs_baseline`` reports achieved MFU / 0.55 — the north star's MFU target —
which is hardware-normalized and therefore comparable across chip types.

Measures the compiled train step on device-resident synthetic batches
(input pipeline excluded, as a synthetic-data reference run would). The
``--steps`` chained steps run inside ONE compiled ``lax.scan`` launch: steps
stay truly sequential (each consumes the previous state; per-step losses are
returned so nothing dead-code-eliminates), while host dispatch overhead —
~100ms/launch through the remote-tunnel TPU attachments used in CI — is paid
once instead of per step. This is the device-throughput number MFU is
defined over.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def make_synthetic_batch(bundle, global_batch, image_size, seq_len, num_classes):
    import numpy as np

    rng = np.random.RandomState(0)
    if bundle.task == "lm":
        vocab = getattr(bundle.module, "vocab_size", 50257)
        toks = rng.randint(0, vocab, (global_batch, seq_len + 1)).astype(np.int32)
        return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    return {
        "image": rng.randn(global_batch, image_size, image_size, 3).astype(np.float32),
        "label": (np.arange(global_batch) % num_classes).astype(np.int32),
    }


def bench(model_name: str = "resnet50", image_size: int = 224,
          per_chip_batch: int = 128, steps: int = 20, warmup: int = 10,
          precision: str = "bf16", quiet: bool = True, seq_len: int = 1024,
          strategy: str | None = None, mesh_spec: dict | None = None,
          remat: bool = False, devices=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, precision as precision_lib, train_loop)
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
    from pytorch_distributed_training_example_tpu.utils import metrics as metrics_lib
    from pytorch_distributed_training_example_tpu.utils.config import from_preset

    mesh = mesh_lib.build_mesh(mesh_spec or {"data": -1}, devices=devices)
    n_chips = mesh.size
    global_batch = per_chip_batch * mesh_lib.dp_size(mesh)
    cfg = from_preset("resnet50_imagenet", global_batch_size=global_batch,
                      precision=precision)
    strategy = strategy or ("fsdp" if "llama" in model_name or "gpt" in model_name
                            else cfg.strategy)

    policy = precision_lib.get_policy(cfg.precision)
    bundle = registry.create_model(model_name, num_classes=cfg.num_classes,
                                   image_size=image_size, seq_len=seq_len,
                                   dtype=policy.compute_dtype,
                                   param_dtype=policy.param_dtype, remat=remat)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=1000)
    rules = sharding_lib.strategy_rules(strategy, bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    task = train_loop.get_task(bundle.task)
    step = train_loop.make_train_step(task)

    batch = make_synthetic_batch(bundle, global_batch, image_size, seq_len,
                                 cfg.num_classes)
    from pytorch_distributed_training_example_tpu.data import prefetch
    batch = prefetch.shard_batch(batch, mesh_lib.batch_sharding(mesh))

    @jax.jit
    def run_steps(state, batch):
        def body(s, _):
            s, metrics = step(s, batch)
            return s, metrics["loss"]
        state, losses = jax.lax.scan(body, state, None, length=steps)
        return state, losses

    with mesh_lib.use_mesh(mesh):
        state, losses = run_steps(state, batch)  # compile + warm
        np.asarray(losses)
        dt = float("inf")
        for _ in range(max(warmup // max(steps, 1), 2)):
            t0 = time.perf_counter()
            state, losses = run_steps(state, batch)
            np.asarray(losses)  # forces execution; per-step losses are real
            dt = min(dt, time.perf_counter() - t0)

    examples_per_sec = global_batch * steps / dt
    per_chip = examples_per_sec / n_chips
    mfu = metrics_lib.mfu(per_chip, bundle.fwd_flops_per_example)
    unit = f"{bundle.examples_unit}/sec/chip"
    if not quiet:
        print(f"# {n_chips} chip(s) ({jax.devices()[0].device_kind}), "
              f"global batch {global_batch}, {dt/steps*1e3:.1f} ms/step, "
              f"mfu {100*mfu:.1f}%", file=sys.stderr)
    workload = "imagenet" if bundle.task == "classification" else f"lm{seq_len}"
    return {
        "metric": f"{model_name}_{workload}_train_throughput",
        "value": round(per_chip, 2),
        "unit": unit,
        "vs_baseline": round(mfu / 0.55, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "chips": n_chips,
            "device": jax.devices()[0].device_kind,
            "global_batch": global_batch,
            "step_ms": round(dt / steps * 1e3, 2),
            "precision": precision,
            "strategy": strategy,
        },
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--per-chip-batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument("--precision", default="bf16")
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--strategy", default=None)
    p.add_argument("--remat", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    result = bench(args.model, args.image_size, args.per_chip_batch,
                   args.steps, args.warmup, args.precision,
                   quiet=not args.verbose, seq_len=args.seq_len,
                   strategy=args.strategy, remat=args.remat)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
