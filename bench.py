#!/usr/bin/env python
"""Driver benchmark: ResNet-50/ImageNet images/sec/chip + MFU (BASELINE.json metric).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

The reference publishes no numbers (BASELINE.json ``published: {}``), so
``vs_baseline`` reports achieved MFU / 0.55 — the north star's MFU target —
which is hardware-normalized and therefore comparable across chip types.

Measures the compiled train step on device-resident synthetic batches
(input pipeline excluded, as a synthetic-data reference run would); steady
state over ``--steps`` steps after ``--warmup`` dispatches.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def bench(model_name: str = "resnet50", image_size: int = 224,
          per_chip_batch: int = 128, steps: int = 30, warmup: int = 10,
          precision: str = "bf16", quiet: bool = True):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, precision as precision_lib, train_loop)
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
    from pytorch_distributed_training_example_tpu.utils import metrics as metrics_lib
    from pytorch_distributed_training_example_tpu.utils.config import from_preset

    n_chips = jax.device_count()
    global_batch = per_chip_batch * n_chips
    cfg = from_preset("resnet50_imagenet", global_batch_size=global_batch,
                      precision=precision)

    policy = precision_lib.get_policy(cfg.precision)
    bundle = registry.create_model(model_name, num_classes=cfg.num_classes,
                                   image_size=image_size,
                                   dtype=policy.compute_dtype,
                                   param_dtype=policy.param_dtype)
    mesh = mesh_lib.build_mesh({"data": -1})
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=1000)
    rules = sharding_lib.strategy_rules(cfg.strategy, bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    task = train_loop.get_task(bundle.task)
    step = jax.jit(train_loop.make_train_step(task), donate_argnums=0)
    warmup = max(warmup, 1)  # at least one dispatch so `metrics` exists

    rng = np.random.RandomState(0)
    batch = {
        "image": rng.randn(global_batch, image_size, image_size, 3).astype(np.float32),
        "label": (np.arange(global_batch) % cfg.num_classes).astype(np.int32),
    }
    from pytorch_distributed_training_example_tpu.data import prefetch
    batch = prefetch.shard_batch(batch, mesh_lib.batch_sharding(mesh))

    with mesh_lib.use_mesh(mesh):
        for _ in range(warmup):
            state, metrics = step(state, batch)
        jax.tree.map(lambda x: x.block_until_ready(), metrics)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        jax.tree.map(lambda x: x.block_until_ready(), metrics)
        dt = time.perf_counter() - t0

    images_per_sec = global_batch * steps / dt
    per_chip = images_per_sec / n_chips
    mfu = metrics_lib.mfu(per_chip, bundle.fwd_flops_per_example)
    if not quiet:
        print(f"# {n_chips} chip(s) ({jax.devices()[0].device_kind}), "
              f"global batch {global_batch}, {dt/steps*1e3:.1f} ms/step, "
              f"mfu {100*mfu:.1f}%", file=sys.stderr)
    return {
        "metric": f"{model_name}_imagenet_train_throughput",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.55, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "chips": n_chips,
            "device": jax.devices()[0].device_kind,
            "global_batch": global_batch,
            "step_ms": round(dt / steps * 1e3, 2),
            "precision": precision,
        },
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--per-chip-batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument("--precision", default="bf16")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    result = bench(args.model, args.image_size, args.per_chip_batch,
                   args.steps, args.warmup, args.precision,
                   quiet=not args.verbose)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
