#!/usr/bin/env python
"""Driver benchmark: ResNet-50/ImageNet images/sec/chip + MFU (BASELINE.json metric).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

The reference publishes no numbers (BASELINE.json ``published: {}``), so
``vs_baseline`` reports achieved MFU / 0.55 — the north star's MFU target —
which is hardware-normalized and therefore comparable across chip types.

Measures the compiled train step on device-resident synthetic batches
(input pipeline excluded, as a synthetic-data reference run would). The
``--steps`` chained steps run inside ONE compiled ``lax.scan`` launch: steps
stay truly sequential (each consumes the previous state; per-step losses are
returned so nothing dead-code-eliminates), while host dispatch overhead —
measured ~75 ms/launch through the remote-tunnel TPU attachment used in CI
(quantified by scan-length slope, BENCH_FLASH_MICRO.json) — is paid once
instead of per step. The default 200 steps bounds that fixed cost to
~0.4 ms/step of reported pessimism (r5; 50 steps cost ViT-B/16 a full
MFU point). This is the device-throughput number MFU is defined over.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time


def make_synthetic_batch(bundle, global_batch, image_size, seq_len, num_classes):
    import numpy as np

    rng = np.random.RandomState(0)
    if bundle.task == "lm":
        vocab = getattr(bundle.module, "vocab_size", 50257)
        toks = rng.randint(0, vocab, (global_batch, seq_len + 1)).astype(np.int32)
        return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    return {
        "image": rng.randn(global_batch, image_size, image_size, 3).astype(np.float32),
        "label": (np.arange(global_batch) % num_classes).astype(np.int32),
    }


def setup_step(model_name: str = "resnet50", image_size: int = 224,
               per_chip_batch: int = 128, precision: str = "bf16",
               seq_len: int = 1024, strategy: str | None = None,
               mesh_spec: dict | None = None, remat: bool = False,
               devices=None, attn_impl: str = "auto",
               moe_capacity_factor: float = 1.25,
               moe_top_k: int = 2, moe_dispatch_impl: str = "gather",
               moe_combine_dtype: str = "fp32",
               moe_router_dtype: str = "fp32",
               moe_router_impl: str = "reference",
               moe_ep_dispatch: str = "replicated",
               moe_ep_overlap_chunks: int = 2,
               remat_policy: str = "nothing", telemetry: bool = False):
    """Build (mesh, state, step_fn, device batch, bundle) exactly as the
    benchmark measures them — shared by bench() and benchmarks/profile_step.py
    so profiles describe the same program the headline numbers time."""
    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, precision as precision_lib, train_loop)
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
    from pytorch_distributed_training_example_tpu.utils.config import from_preset

    mesh = mesh_lib.build_mesh(mesh_spec or {"data": -1}, devices=devices)
    global_batch = per_chip_batch * mesh_lib.dp_size(mesh)
    cfg = from_preset("resnet50_imagenet", global_batch_size=global_batch,
                      precision=precision)
    strategy = strategy or ("fsdp" if "llama" in model_name or "gpt" in model_name
                            else cfg.strategy)

    policy = precision_lib.get_policy(cfg.precision)
    bundle = registry.create_model(model_name, num_classes=cfg.num_classes,
                                   image_size=image_size, seq_len=seq_len,
                                   dtype=policy.compute_dtype,
                                   param_dtype=policy.param_dtype, remat=remat,
                                   remat_policy=remat_policy,
                                   attn_impl=attn_impl,
                                   moe_capacity_factor=moe_capacity_factor,
                                   moe_top_k=moe_top_k,
                                   moe_dispatch_impl=moe_dispatch_impl,
                                   moe_combine_dtype=moe_combine_dtype,
                                   moe_router_dtype=moe_router_dtype,
                                   moe_router_impl=moe_router_impl,
                                   moe_ep_dispatch=moe_ep_dispatch,
                                   moe_ep_overlap_chunks=moe_ep_overlap_chunks,
                                   logits_dtype=policy.logits_dtype)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=1000)
    rules = sharding_lib.strategy_rules(strategy, bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    task = train_loop.get_task(bundle.task)
    step = train_loop.make_train_step(task, health=telemetry)

    batch = make_synthetic_batch(bundle, global_batch, image_size, seq_len,
                                 cfg.num_classes)
    from pytorch_distributed_training_example_tpu.data import prefetch
    batch = prefetch.shard_batch(batch, mesh_lib.batch_sharding(mesh))
    return {"mesh": mesh, "state": state, "step": step, "batch": batch,
            "bundle": bundle, "cfg": cfg, "strategy": strategy,
            "global_batch": global_batch}


def bench(model_name: str = "resnet50", image_size: int = 224,
          per_chip_batch: int = 128, steps: int = 200, warmup: int = 10,
          precision: str = "bf16", quiet: bool = True, seq_len: int = 1024,
          strategy: str | None = None, mesh_spec: dict | None = None,
          remat: bool = False, devices=None, attn_impl: str = "auto",
          moe_capacity_factor: float = 1.25, moe_top_k: int = 2,
          moe_dispatch_impl: str = "gather", moe_combine_dtype: str = "fp32",
          moe_router_dtype: str = "fp32", moe_router_impl: str = "reference",
          moe_ep_dispatch: str = "replicated",
          moe_ep_overlap_chunks: int = 2,
          remat_policy: str = "nothing", telemetry: bool = False,
          fleet_obs: bool = False):
    import jax
    import numpy as np

    from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
    from pytorch_distributed_training_example_tpu.utils import metrics as metrics_lib

    su = setup_step(model_name, image_size, per_chip_batch, precision, seq_len,
                    strategy, mesh_spec, remat, devices, attn_impl,
                    moe_capacity_factor=moe_capacity_factor,
                    moe_top_k=moe_top_k, moe_dispatch_impl=moe_dispatch_impl,
                    moe_combine_dtype=moe_combine_dtype,
                    moe_router_dtype=moe_router_dtype,
                    moe_router_impl=moe_router_impl,
                    moe_ep_dispatch=moe_ep_dispatch,
                    moe_ep_overlap_chunks=moe_ep_overlap_chunks,
                    remat_policy=remat_policy, telemetry=telemetry)
    mesh, state, step, batch, bundle = (su["mesh"], su["state"], su["step"],
                                        su["batch"], su["bundle"])
    strategy, global_batch = su["strategy"], su["global_batch"]
    n_chips = mesh.size

    # Donate the state like the real trainer does (core/trainer.py
    # donate_argnums=0): without it the scan holds input AND output state
    # resident, which alone put the 520M-param MoE row out of HBM.
    @functools.partial(jax.jit, donate_argnums=0)
    def run_steps(state, batch):
        def body(s, _):
            s, metrics = step(s, batch)
            # With telemetry on, return the WHOLE metrics dict: returning
            # only the loss would let XLA dead-code-eliminate the health
            # pack, and the "telemetry overhead" measurement would time
            # nothing. All entries are scalars, so the stacked output is
            # a few KB either way.
            return s, (metrics if telemetry else metrics["loss"])
        return jax.lax.scan(body, state, None, length=steps)

    def fetch(out):
        # Force execution (and a host round-trip, like the trainer's
        # log_every device_get). With telemetry, `out` is the full metrics
        # dict — fetching all of it keeps the health pack live.
        return {k: np.asarray(v) for k, v in out.items()} if telemetry \
            else np.asarray(out)

    # Fleet-observability overhead mode (--fleet-obs): run the EXACT host-side
    # per-step work the trainer adds for utils/fleetobs.py — flight-recorder
    # ring append, buffered step-row write, straggler-monitor median check —
    # inside the timed region, once per scanned step, with a live /metrics
    # HTTP server scrape-able throughout. The step_ms delta vs a plain run is
    # the measured fleet-layer tax (BASELINE.md; expected ~0: the ring is a
    # deque append and the writer batches 32 rows per syscall).
    fleet = None
    if fleet_obs:
        import tempfile

        from pytorch_distributed_training_example_tpu.utils import fleetobs

        fdir = tempfile.mkdtemp(prefix="bench_fleetobs_")
        fleet = {
            "server": fleetobs.MetricsServer(port=0).start(),
            "flight": fleetobs.FlightRecorder(256),
            "monitor": fleetobs.StragglerMonitor(),
            "writer": fleetobs.StepRowWriter(fdir, rank=0, attempt=1,
                                             meta={"bench": model_name}),
            "dir": fdir, "gstep": 0, "host_s": float("inf"),
        }

    def fleet_step_work(rep_s: float) -> float:
        """The trainer's per-step fleetobs host work, repeated ``steps``
        times (the scan ran that many device steps); returns seconds spent.
        Per-rep (= the trainer's log cadence) it also refreshes the gauges
        behind the live endpoint and the atomic progress.json."""
        from pytorch_distributed_training_example_tpu.utils import fleetobs

        per_step = rep_s / steps
        f0 = time.perf_counter()
        for _ in range(steps):
            g = fleet["gstep"]
            fleet["gstep"] = g + 1
            row = {"total_s": per_step, "input_wait_s": 0.0,
                   "compute_s": per_step, "checkpoint_s": 0.0}
            fleet["flight"].record_timing(g, **row)
            fleet["writer"].add({"step": g, **row})
            fleet["monitor"].observe(g, total_s=per_step, input_wait_s=0.0)
        fleet["server"].update(step=fleet["gstep"], step_time_s=per_step)
        fleetobs.write_progress(fleet["dir"],
                               {"step": fleet["gstep"], "status": "bench"})
        return time.perf_counter() - f0

    with mesh_lib.use_mesh(mesh):
        compiled = run_steps.lower(state, batch).compile()
        state, out = compiled(state, batch)  # warm (first run pays setup)
        fetch(out)
        dt = float("inf")
        for _ in range(max(warmup // max(steps, 1), 2)):
            t0 = time.perf_counter()
            state, out = compiled(state, batch)
            fetch(out)  # forces execution; per-step losses are real
            if fleet is not None:
                fleet["host_s"] = min(
                    fleet["host_s"],
                    fleet_step_work(time.perf_counter() - t0))
            dt = min(dt, time.perf_counter() - t0)
    if fleet is not None:
        import shutil

        fleet["writer"].flush()
        fleet["server"].stop()
        shutil.rmtree(fleet["dir"], ignore_errors=True)
    try:
        ca = compiled.cost_analysis() or {}
        if isinstance(ca, list):  # XLA:CPU returns [dict], TPU a dict
            ca = ca[0] if ca else {}
    except Exception:
        ca = {}

    examples_per_sec = global_batch * steps / dt
    per_chip = examples_per_sec / n_chips
    mfu = metrics_lib.mfu(per_chip, bundle.fwd_flops_per_example)
    unit = f"{bundle.examples_unit}/sec/chip"

    # Roofline placement from XLA's own cost model: is this program compute-
    # or HBM-bound on this chip, and how close to the bandwidth peak does it
    # run? (SURVEY.md §6; the ResNet-50/v5e step measures ~95% of peak HBM
    # BW at arithmetic intensity ~70 flops/byte vs a ~240 ridge point.)
    roofline = {}
    step_s = dt / steps
    if ca.get("bytes accessed") and ca.get("flops"):
        # XLA's cost model counts a lax.scan body ONCE regardless of trip
        # count (verified: the 1-step and 10-step lowerings of this program
        # both report flops 3.06e12, bytes 4.5e10), and reports PER-DEVICE
        # (post-GSPMD-partitioning) numbers — so these are already per-step,
        # per-chip.
        bytes_step = ca["bytes accessed"]
        flops_step = ca["flops"]
        peak_bw = metrics_lib.peak_hbm_gbps()
        intensity = flops_step / bytes_step
        ridge = metrics_lib.peak_flops_per_chip() / (peak_bw * 1e9)
        # "bytes accessed" counts LOGICAL operand bytes; fused reads are
        # double-counted, so bytes/time is an UPPER BOUND on real HBM
        # traffic rate and can exceed the physical peak. Name the field for
        # what it is and carry the source tag, so the artifact is
        # self-describing (ADVICE r2 / VERDICT r2 #6).
        modeled_gbps = bytes_step / step_s / 1e9
        roofline = {
            "hbm_bytes_per_step": round(bytes_step / 1e9, 3),
            "bytes_source": "xla_cost_model_upper_bound",
            "modeled_hbm_gbps": round(modeled_gbps, 1),
            "modeled_bw_fraction_of_peak": round(
                min(modeled_gbps / peak_bw, 1.0), 3),
            "peak_hbm_gbps": peak_bw,
            "xla_flops_per_step": round(flops_step / 1e12, 3),
            "arithmetic_intensity": round(intensity, 1),
            "ridge_intensity": round(ridge, 1),
            "bound": "hbm" if intensity < ridge else "compute",
        }
    if not quiet:
        print(f"# {n_chips} chip(s) ({jax.devices()[0].device_kind}), "
              f"global batch {global_batch}, {dt/steps*1e3:.1f} ms/step, "
              f"mfu {100*mfu:.1f}%", file=sys.stderr)
    workload = "imagenet" if bundle.task == "classification" else f"lm{seq_len}"
    return {
        "metric": f"{model_name}_{workload}_train_throughput",
        "value": round(per_chip, 2),
        "unit": unit,
        "vs_baseline": round(mfu / 0.55, 4),
        "extra": {
            "mfu": round(mfu, 4),
            "chips": n_chips,
            "device": jax.devices()[0].device_kind,
            "global_batch": global_batch,
            "step_ms": round(dt / steps * 1e3, 2),
            "precision": precision,
            "strategy": strategy,
            "attn_impl": attn_impl,
            **({"telemetry": True} if telemetry else {}),
            **({"fleet_obs": True,
                "fleetobs_host_us_per_step": round(
                    fleet["host_s"] / steps * 1e6, 2)}
               if fleet is not None else {}),
            **({"moe_dispatch_impl": moe_dispatch_impl,
                "moe_top_k": moe_top_k,
                "moe_combine_dtype": moe_combine_dtype,
                "moe_router_dtype": moe_router_dtype,
                "moe_router_impl": moe_router_impl,
                "moe_ep_dispatch": moe_ep_dispatch,
                "moe_ep_overlap_chunks": moe_ep_overlap_chunks,
                "moe_capacity_factor": moe_capacity_factor}
               if "moe" in model_name else {}),
            **({"remat_policy": remat_policy}
               if remat_policy != "nothing" else {}),
            **({"roofline": roofline} if roofline else {}),
        },
    }


def _synthetic_jpeg_tree(root: str, num_images: int = 256, classes: int = 8,
                         size=(500, 375)) -> str:
    """Write an ImageNet-shaped JPEG tree (typical ~500x375 images) once."""
    import os

    import numpy as np
    from PIL import Image

    marker = os.path.join(root, f".complete_{num_images}_{size[0]}")
    if os.path.exists(marker):
        return root
    rng = np.random.default_rng(0)
    w, h = size
    for i in range(num_images):
        cdir = os.path.join(root, f"class_{i % classes:03d}")
        os.makedirs(cdir, exist_ok=True)
        yy, xx = np.mgrid[0:h, 0:w]
        base = np.stack([(xx + i * 7) % 256, (yy + i * 13) % 256,
                         np.full_like(xx, (i * 29) % 256)], -1)
        arr = np.clip(base + rng.normal(0, 8, base.shape), 0, 255).astype("uint8")
        Image.fromarray(arr).save(os.path.join(cdir, f"img_{i:05d}.jpg"),
                                  quality=90)
    open(marker, "w").close()
    return root


def bench_input(data_path: str | None, image_size: int = 224,
                batch_size: int = 128, batches: int = 8, workers: int = 8,
                native: bool = True):
    """Input pipeline alone: decode+augment+collate images/sec on this host."""
    import os

    from pytorch_distributed_training_example_tpu.data import (
        datasets as ds_lib, loader as loader_lib, native_loader,
        sampler as sampler_lib)

    if not data_path:
        # Cover the full measured run: with only ~2 batches on disk, the
        # prefetcher would decode everything during warmup and the timed
        # loop would measure buffer copies, not decode throughput.
        data_path = _synthetic_jpeg_tree(
            "/tmp/bench_jpeg_tree",
            num_images=max(256, (batches + 1) * batch_size))
    ds = ds_lib.build_dataset("imagenet", data_path, train=True,
                              image_size=image_size)
    n_batches = min(batches, len(ds) // batch_size)
    if n_batches < 2:
        raise ValueError(
            f"dataset at {data_path!r} has {len(ds)} images; need at least "
            f"2*batch_size={2 * batch_size} to measure input throughput")
    sampler = sampler_lib.ShardedSampler(len(ds), shuffle=True, drop_last=True)
    dl = loader_lib.build_image_loader(ds, sampler, batch_size,
                                       workers=workers, native=native)
    use_native = isinstance(dl, native_loader.NativeDataLoader)
    it = iter(dl)
    next(it)  # warm: thread spin-up, first-touch page faults
    t0 = time.perf_counter()
    n = 0
    for b in it:
        n += len(b["label"])
        if n >= (n_batches - 1) * batch_size:
            break
    dt = time.perf_counter() - t0
    out = {"input_images_per_sec": round(n / dt, 1),
           "input_loader": "native_jpeg" if use_native else "python",
           "input_workers": workers,
           "host_cpus": os.cpu_count()}
    if use_native:
        out["input_decode_errors"] = dl.engine.decode_errors()
    return out


def bench_e2e(data_path: str | None, image_size: int = 224,
              per_chip_batch: int = 128, steps: int = 8,
              precision: str = "bf16", workers: int = 8):
    """End-to-end: real JPEG loader -> device_put -> compiled train step.

    The number SURVEY.md §7(a) asks for: throughput INCLUDING the input
    pipeline, vs the device-only number the headline measures.
    """
    import jax

    from pytorch_distributed_training_example_tpu.core import (
        mesh as mesh_lib, optim, precision as precision_lib, train_loop)
    from pytorch_distributed_training_example_tpu.data import (
        datasets as ds_lib, loader as loader_lib, prefetch,
        sampler as sampler_lib)
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.parallel import (
        sharding as sharding_lib)
    from pytorch_distributed_training_example_tpu.utils.config import from_preset

    mesh = mesh_lib.build_mesh({"data": -1})
    global_batch = per_chip_batch * mesh_lib.dp_size(mesh)
    if not data_path:
        data_path = _synthetic_jpeg_tree("/tmp/bench_jpeg_tree",
                                         num_images=max(256, 2 * global_batch))
    cfg = from_preset("resnet50_imagenet", global_batch_size=global_batch,
                      precision=precision)
    policy = precision_lib.get_policy(cfg.precision)
    bundle = registry.create_model("resnet50", num_classes=cfg.num_classes,
                                   image_size=image_size,
                                   dtype=policy.compute_dtype,
                                   param_dtype=policy.param_dtype)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=1000)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    step = jax.jit(train_loop.make_train_step(train_loop.get_task(bundle.task)),
                   donate_argnums=0)

    ds = ds_lib.build_dataset("imagenet", data_path, train=True,
                              image_size=image_size)
    if len(ds) < global_batch:
        raise ValueError(
            f"dataset at {data_path!r} has {len(ds)} images < global batch "
            f"{global_batch}; point --data-path at a larger tree")
    sampler = sampler_lib.ShardedSampler(len(ds), shuffle=True, drop_last=True)
    dl = loader_lib.build_image_loader(ds, sampler, global_batch,
                                       workers=workers)
    total = steps + 2
    t0 = None
    n = 0
    done = 0
    with mesh_lib.use_mesh(mesh):
        while done < total:
            dl.set_epoch(done)  # cycle epochs if the tree is small
            for batch in prefetch.device_prefetch(
                    dl, mesh_lib.batch_sharding(mesh)):
                state, metrics = step(state, batch)
                done += 1
                if done == 2:  # past compile + warmup
                    jax.tree.map(lambda x: x.block_until_ready(), metrics)
                    t0 = time.perf_counter()
                elif done > 2:
                    n += global_batch
                if done >= total:
                    break
        jax.tree.map(lambda x: x.block_until_ready(), metrics)
    dt = time.perf_counter() - t0

    # Measured host->device bandwidth for one batch (device_put + forced
    # consumption — transfers complete lazily on some attachments). On the
    # CI chip this runs through a network tunnel at ~30 MB/s, which caps any
    # input-included number far below what a real TPU host's DMA achieves;
    # reporting it makes the e2e figure interpretable.
    import numpy as np

    probe = np.zeros((global_batch, image_size, image_size, 3), np.float32)
    consume = jax.jit(lambda b: b["x"].sum())
    with mesh_lib.use_mesh(mesh):
        # Same-shape warmup (jit caches per shape) on a distinct array, so
        # the timed run measures pure transfer, not compilation.
        warm = prefetch.shard_batch(
            {"x": np.ones_like(probe)}, mesh_lib.batch_sharding(mesh))
        consume(warm).block_until_ready()
        t0 = time.perf_counter()
        dev = prefetch.shard_batch({"x": probe}, mesh_lib.batch_sharding(mesh))
        consume(dev).block_until_ready()
        h2d = probe.nbytes / (time.perf_counter() - t0)
    return {"e2e_images_per_sec_per_chip": round(n / dt / mesh.size, 1),
            "e2e_global_batch": global_batch,
            "e2e_h2d_gbytes_per_sec": round(h2d / 1e9, 3)}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--per-chip-batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=200,
                   help="scan length; long scans amortize the attachment's "
                        "~75 ms fixed per-launch dispatch below 0.4 ms/step")
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument("--precision", default="bf16")
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--strategy", default=None)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat-policy", default="nothing",
                   choices=["nothing", "dots", "dots_no_batch", "attn_out"],
                   help="checkpoint policy under --remat (Llama family): "
                        "A/B the save-list for the backward recompute")
    p.add_argument("--moe-top-k", type=int, default=2,
                   help="experts routed per token (llama_moe family)")
    p.add_argument("--moe-dispatch", default="gather",
                   choices=["sort", "gather", "einsum", "dropless"],
                   dest="moe_dispatch",
                   help="MoE dispatch formulation (parallel/moe.py)")
    p.add_argument("--moe-router-dtype", default="fp32",
                   choices=["fp32", "bf16"], dest="moe_router_dtype",
                   help="router logits-matmul precision (fp32 = ST-MoE "
                        "exact default; bf16 keeps fp32 accumulation and "
                        "softmax/top-k)")
    p.add_argument("--moe-router-impl", default="reference",
                   choices=["reference", "fused"], dest="moe_router_impl",
                   help="router softmax/top-k/gates: reference XLA chain or "
                        "the fused single-pass Pallas kernel "
                        "(ops/fused_router.py)")
    p.add_argument("--moe-combine", default="fp32", choices=["fp32", "bf16"],
                   help="combine-einsum precision (router stays fp32)")
    p.add_argument("--moe-ep-dispatch", default="replicated",
                   choices=["replicated", "a2a", "a2a_overlap"],
                   dest="moe_ep_dispatch",
                   help="dropless EP transport: replicated weights, "
                        "all-to-all token shards, or chunked a2a/gmm "
                        "overlap (parallel/moe.py)")
    p.add_argument("--moe-ep-overlap-chunks", type=int, default=2,
                   dest="moe_ep_overlap_chunks",
                   help="a2a_overlap double-buffer windows over the token dim")
    p.add_argument("--moe-capacity-factor", type=float, default=1.25,
                   help="MoE expert capacity factor (llama_moe rows)")
    p.add_argument("--attn-impl", default="auto",
                   choices=["auto", "xla", "flash", "ring", "ring_zigzag",
                            "ring_allgather", "ulysses"])
    p.add_argument("--telemetry", action="store_true",
                   help="compile the on-device health pack into the step "
                        "(utils/telemetry.py) — measures its overhead vs "
                        "the default row")
    p.add_argument("--fleet-obs", action="store_true", dest="fleet_obs",
                   help="run the fleet-observability host work "
                        "(utils/fleetobs.py flight recorder + step rows + "
                        "straggler monitor + live /metrics endpoint) inside "
                        "the timed loop — measures its overhead vs the "
                        "default row")
    p.add_argument("--no-measured-roofline", action="store_true",
                   help="skip the xplane-measured roofline pass (resnet50 "
                        "headline only; ~2 min extra)")
    p.add_argument("--include-input", action="store_true",
                   help="also measure loader-only and end-to-end throughput "
                        "over a real JPEG tree (synthetic if no --data-path)")
    p.add_argument("--no-lm", action="store_true",
                   help="skip the compute-bound GPT-2 companion row")
    p.add_argument("--data-path", default=None)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    result = bench(args.model, args.image_size, args.per_chip_batch,
                   args.steps, args.warmup, args.precision,
                   quiet=not args.verbose, seq_len=args.seq_len,
                   strategy=args.strategy, remat=args.remat,
                   attn_impl=args.attn_impl,
                   moe_capacity_factor=args.moe_capacity_factor,
                   moe_top_k=args.moe_top_k,
                   moe_dispatch_impl=args.moe_dispatch,
                   moe_combine_dtype=args.moe_combine,
                   moe_router_dtype=args.moe_router_dtype,
                   moe_router_impl=args.moe_router_impl,
                   moe_ep_dispatch=args.moe_ep_dispatch,
                   moe_ep_overlap_chunks=args.moe_ep_overlap_chunks,
                   remat_policy=args.remat_policy, telemetry=args.telemetry,
                   fleet_obs=args.fleet_obs)
    if (args.model == "resnet50" and not args.no_measured_roofline):
        # Measured-bytes roofline (VERDICT r3 #3): per-executed-op buffer
        # traffic from the scheduled HLO joined with xplane durations —
        # replaces the cost-model upper bound that could exceed physical
        # peak (the r3 936>819 GB/s inconsistency).
        import jax

        if jax.default_backend() != "cpu":
            import os
            import sys as _sys
            _sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
            from profile_step import profile as _profile

            prof = _profile(args.model, image_size=args.image_size,
                            per_chip_batch=args.per_chip_batch,
                            precision=args.precision, steps=3,
                            strategy=args.strategy, remat=args.remat,
                            attn_impl=args.attn_impl)
            result["extra"]["roofline_measured"] = prof["roofline_measured"]
    if args.model == "resnet50" and not args.no_lm:
        # The ResNet-50 step is HBM-bound on small chips (see roofline
        # extras); record the compute-bound LM headline alongside it.
        import jax

        if jax.default_backend() != "cpu":
            # per-chip batch 24: r4 sweep peak with the chunked-bwd flash
            # kernels (63.6% MFU vs 62.4% at the r3 batch of 16).
            lm = bench("gpt2", per_chip_batch=24, steps=200, warmup=4,
                       precision=args.precision, seq_len=1024, quiet=True)
            result["extra"]["lm"] = {
                "metric": lm["metric"], "value": lm["value"],
                "unit": lm["unit"], "mfu": lm["extra"]["mfu"],
                "step_ms": lm["extra"]["step_ms"],
                "global_batch": lm["extra"]["global_batch"],
            }
    if args.include_input:
        result["extra"].update(bench_input(
            args.data_path, args.image_size, args.per_chip_batch,
            workers=args.workers))
        result["extra"].update(bench_e2e(
            args.data_path, args.image_size, args.per_chip_batch,
            precision=args.precision, workers=args.workers))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
