#!/usr/bin/env python
"""Training entrypoint — CLI-compatible with the reference's ``main.py``.

The north-star contract (BASELINE.json): ``python main.py --distributed``
launches unchanged on a TPU slice. Flag surface follows the reference's
argparse conventions (SURVEY.md §2a #1): epochs/batch-size/lr/data-path/
workers/resume, plus ``--config`` presets for the five reference workloads
and mesh/strategy flags for the TPU-native parallelism that replaces DDP.

Single-process mode (no ``--distributed``) is the reference's CPU-runnable
dev path (SURVEY.md §3.5): same compiled step on whatever single host
process + devices exist, no rendezvous.
"""

from __future__ import annotations

import argparse
import dataclasses


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native distributed training")
    p.add_argument("--distributed", action="store_true",
                   help="multi-host mode: rendezvous via jax.distributed.initialize "
                        "(the init_process_group('nccl') equivalent)")
    p.add_argument("--config", default=None,
                   help="preset name (resnet18_cifar10, resnet50_imagenet, "
                        "vit_b16_imagenet, gpt2_124m, llama3_8b)")
    p.add_argument("--model", default=None)
    p.add_argument("--dataset", default=None)
    p.add_argument("--data-path", default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None, dest="global_batch_size",
                   help="GLOBAL batch size (split across hosts/chips)")
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr-schedule", default=None,
                   choices=["cosine", "step", "constant"],
                   help="step = the reference ImageNet StepLR recipe "
                        "(lr * gamma^(epoch // step-epochs))")
    p.add_argument("--lr-step-epochs", type=int, default=None)
    p.add_argument("--lr-gamma", type=float, default=None)
    p.add_argument("--weight-decay", type=float, default=None)
    p.add_argument("--optimizer", default=None, choices=["sgd", "adamw"])
    p.add_argument("--precision", default=None,
                   choices=["fp32", "bf16", "pure_bf16", "fp16"])
    p.add_argument("--strategy", default=None,
                   help="dp | fsdp | model-specific (e.g. fsdp_tp)")
    p.add_argument("--mesh", default=None,
                   help="axis sizes as k=v pairs, e.g. 'data=2,fsdp=4' "
                        "(-1 absorbs remaining devices; aliases seq/cp/tp/"
                        "ep/pp map to context/model/expert/stage)")
    p.add_argument("--mesh-seq", type=int, default=None, dest="mesh_context",
                   help="sequence/context-parallel degree (shorthand for "
                        "--mesh seq=N; ring attention shards S over it)")
    p.add_argument("--remat", action="store_true", default=None,
                   help="gradient checkpointing")
    p.add_argument("--remat-policy", default=None, dest="remat_policy",
                   choices=["nothing", "dots", "dots_no_batch", "attn_out"],
                   help="checkpoint policy under --remat (Llama family): "
                        "what to save across the backward recompute")
    p.add_argument("--grad-accum", type=int, default=None,
                   dest="grad_accum_steps",
                   help="gradient-accumulation microbatches per step")
    p.add_argument("--attn-impl", default=None,
                   choices=["auto", "xla", "flash", "ring", "ring_zigzag",
                            "ring_allgather", "ulysses"],
                   help="attention kernel: Pallas flash, ring (context-"
                        "parallel ppermute; ring_allgather = all-gather-KV "
                        "fallback), Ulysses all-to-all, or plain XLA")
    p.add_argument("--seq-len", type=int, default=None)
    p.add_argument("--moe-top-k", type=int, default=None, dest="moe_top_k",
                   help="experts routed per token (llama_moe family)")
    p.add_argument("--moe-capacity-factor", type=float, default=None,
                   dest="moe_capacity_factor",
                   help="expert capacity = cf * T * top_k / E (tokens beyond "
                        "it are dropped, Switch-style)")
    p.add_argument("--moe-dispatch", default=None, dest="moe_dispatch_impl",
                   choices=["sort", "gather", "einsum", "dropless"],
                   help="MoE token-dispatch formulation (parallel/moe.py): "
                        "sort (argsort+segment), gather (slot table), "
                        "einsum (one-hot masks, GSPMD oracle), or dropless "
                        "(ragged Pallas grouped matmul — no capacity "
                        "factor, no dropped tokens)")
    p.add_argument("--moe-combine", default=None, dest="moe_combine_dtype",
                   choices=["fp32", "bf16"],
                   help="combine-einsum precision (bf16 halves combine "
                        "bandwidth; router softmax/top-k always fp32)")
    p.add_argument("--moe-router-dtype", default=None, dest="moe_router_dtype",
                   choices=["fp32", "bf16"],
                   help="router logits-matmul precision (fp32 = ST-MoE "
                        "exact default; bf16 keeps fp32 accumulation and "
                        "fp32 softmax/top-k)")
    p.add_argument("--moe-router-impl", default=None, dest="moe_router_impl",
                   choices=["reference", "fused"],
                   help="router softmax/top-k/gates: reference XLA chain "
                        "(default) or the fused single-pass Pallas kernel "
                        "(ops/fused_router.py)")
    p.add_argument("--moe-ep-dispatch", default=None, dest="moe_ep_dispatch",
                   choices=["replicated", "a2a", "a2a_overlap"],
                   help="dropless expert-parallel transport: replicated "
                        "(every device runs all experts), a2a (all-to-all "
                        "token shards to local expert weights), or "
                        "a2a_overlap (chunked a2a double-buffered against "
                        "the grouped matmul)")
    p.add_argument("--moe-ep-overlap-chunks", type=int, default=None,
                   dest="moe_ep_overlap_chunks",
                   help="a2a_overlap double-buffer windows over the token "
                        "dim (>= 2 overlaps; the last window may be torn)")
    p.add_argument("--dropout", type=float, default=None,
                   help="model dropout rate (families that support it)")
    p.add_argument("--tensorboard-dir", type=str, default=None,
                   dest="tensorboard_dir",
                   help="export metric scalars as TensorBoard events here")
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--log-every", type=int, default=None)
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="cap steps per epoch (smoke/bench runs)")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every-steps", type=int, default=None,
                   help="also checkpoint every N optimizer steps (mid-epoch; "
                        "resume continues at the exact next sample)")
    p.add_argument("--resume", default=None, nargs="?", const="auto",
                   help="checkpoint dir or 'auto' (newest committed)")
    p.add_argument("--elastic", action="store_true", default=None,
                   help="elastic resume: accept a checkpoint written under a "
                        "different world size — rebuild the mesh at the "
                        "surviving device set and rescale the batch geometry "
                        "under --elastic-policy (utils/elastic.py)")
    p.add_argument("--elastic-policy", default=None, dest="elastic_policy",
                   choices=["keep_global_batch", "scale_lr"],
                   help="batch policy on a world-size change: keep the "
                        "global batch via gradient accumulation (exact "
                        "trajectory) or shrink/grow it with linear LR "
                        "scaling")
    p.add_argument("--evaluate", action="store_true",
                   help="evaluation only (use with --resume to score a "
                        "checkpoint); no training")
    p.add_argument("--telemetry", action="store_true", default=None,
                   help="unified telemetry: on-device health pack in the "
                        "metrics rows, span timeline + goodput accounting "
                        "(trace_events.json/goodput.json in the checkpoint "
                        "dir), anomaly guard")
    p.add_argument("--health-every", type=int, default=None,
                   dest="health_every",
                   help="with --telemetry: also fetch/check the health pack "
                        "every N steps (0 = ride the log-every fetch only)")
    p.add_argument("--anomaly-action", default=None, dest="anomaly_action",
                   choices=["abort", "continue", "rollback"],
                   help="on a non-finite health scalar: dump a diagnostic "
                        "bundle then abort (raise), keep training, or "
                        "rollback (restore last committed checkpoint and "
                        "continue past the poisoned batches, bounded by "
                        "--rollback-budget)")
    p.add_argument("--rollback-budget", type=int, default=None,
                   dest="rollback_budget",
                   help="max anomaly rollbacks per run before escalating "
                        "to abort")
    p.add_argument("--watchdog-timeout", type=float, default=None,
                   dest="watchdog_timeout",
                   help="seconds without step progress before the watchdog "
                        "dumps stacks and aborts")
    p.add_argument("--chaos", default=None,
                   help="deterministic fault injection spec, e.g. "
                        "'sigterm@step=7,ckpt_io_error@save=2,"
                        "nan_grad@step=5,loader_stall@batch=3,"
                        "truncate_ckpt@save=1' (utils/chaos.py); "
                        "append :rank=R to fire on one rank only")
    p.add_argument("--straggler-threshold", type=float, default=None,
                   dest="straggler_threshold",
                   help="warn when a step's host-local wait exceeds "
                        "(threshold-1) x the median step time "
                        "(utils/fleetobs.py; default 2.0)")
    p.add_argument("--flightrec-steps", type=int, default=None,
                   dest="flightrec_steps",
                   help="flight-recorder ring size: last-N step records "
                        "dumped on anomaly/preemption/host-loss exits")
    p.add_argument("--metrics-port", type=int, default=None,
                   dest="metrics_port",
                   help="rank-0 Prometheus endpoint port (0 = ephemeral, "
                        "logged at startup); also enables progress.json")
    p.add_argument("--chaos-seed", type=int, default=None, dest="chaos_seed",
                   help="seed for chaos randomness (defaults to --seed)")
    p.add_argument("--profile-steps", default=None,
                   help="'start:stop' global-step range to trace")
    p.add_argument("--fault-inject", default=None,
                   help="'rank:step' — hard-kill that process before the "
                        "given global step (recovery testing)")
    p.add_argument("--num-classes", type=int, default=None)
    p.add_argument("--warmup-epochs", type=float, default=None,
                   help="linear LR warmup length (fractions allowed)")
    p.add_argument("--momentum", type=float, default=None,
                   help="SGD momentum")
    p.add_argument("--label-smoothing", type=float, default=None)
    p.add_argument("--grad-clip", type=float, default=None,
                   help="global-norm gradient clip (0 disables)")
    p.add_argument("--pp-microbatches", type=int, default=None,
                   dest="pp_microbatches",
                   help="GPipe microbatches for --strategy pp")
    p.add_argument("--no-native-loader", action="store_false", default=None,
                   dest="native_loader",
                   help="disable the C++ batch engine even when available")
    p.add_argument("--eval-every-epochs", type=int, default=None)
    p.add_argument("--checkpoint-every-epochs", type=int, default=None)
    p.add_argument("--profile-dir", default=None,
                   help="where --profile-steps traces are written")
    p.add_argument("--coordinator", default=None,
                   help="coordinator address host:port (else env)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--serve", action="store_true", default=None,
                   help="run the continuous-batching decode engine (serve/) "
                        "instead of training; --resume restores params only")
    p.add_argument("--serve-page-size", type=int, default=None,
                   dest="serve_page_size",
                   help="KV cache page size in tokens (default 16)")
    p.add_argument("--serve-num-pages", type=int, default=None,
                   dest="serve_num_pages",
                   help="KV cache pool size in pages (default 128)")
    p.add_argument("--serve-max-model-len", type=int, default=None,
                   dest="serve_max_model_len",
                   help="per-request token cap; 0 = model/cache capacity")
    p.add_argument("--serve-decode-buckets", default=None,
                   dest="serve_decode_buckets",
                   help="comma-separated padded decode batch sizes")
    p.add_argument("--serve-prompt-buckets", default=None,
                   dest="serve_prompt_buckets",
                   help="comma-separated padded prefill prompt lengths")
    p.add_argument("--serve-requests", type=int, default=None,
                   dest="serve_requests",
                   help="number of synthetic requests to drain")
    p.add_argument("--serve-rate", type=float, default=None, dest="serve_rate",
                   help="open-loop Poisson arrival rate (req/s); 0 = all at "
                        "t=0 (saturation)")
    p.add_argument("--serve-drain-timeout", type=float, default=None,
                   dest="serve_drain_timeout",
                   help="on SIGTERM, seconds to let in-flight sequences "
                        "finish decoding before exiting 75 (graceful "
                        "preemption of a serving session)")
    p.add_argument("--serve-prefix-cache", action="store_true", default=None,
                   dest="serve_prefix_cache",
                   help="share prompt-prefix KV pages across requests "
                        "(copy-on-write; serve/prefix_cache.py)")
    p.add_argument("--serve-prefill-chunk", type=int, default=None,
                   dest="serve_prefill_chunk",
                   help="chunked prefill window in tokens (multiple of the "
                        "page size); 0 = whole prompt in one program")
    p.add_argument("--serve-disaggregate", action="store_true", default=None,
                   dest="serve_disaggregate",
                   help="split serving into a prefill-role and a decode-role "
                        "engine with explicit KV-page handoff")
    p.add_argument("--serve-replicas", type=int, default=None,
                   dest="serve_replicas",
                   help="serve replicas behind the prefix-affinity router "
                        "(serve/router.py); 1 = no router")
    p.add_argument("--serve-route", default=None, dest="serve_route",
                   choices=["affinity", "least_loaded"],
                   help="replica placement policy")
    p.add_argument("--serve-templates", type=int, default=None,
                   dest="serve_templates",
                   help="shared-prefix prompt templates in the synthetic "
                        "stream (0 = fully random prompts)")
    p.add_argument("--serve-zipf-a", type=float, default=None,
                   dest="serve_zipf_a",
                   help="Zipf exponent for template popularity")
    p.add_argument("--serve-prefix-len", default=None, dest="serve_prefix_len",
                   help="template length range, \"min:max\" tokens")
    p.add_argument("--serve-spec-decode", default=None,
                   dest="serve_spec_decode",
                   choices=["off", "ngram", "draft"],
                   help="speculative decoding proposer: self-drafting n-gram "
                        "lookup or a separate draft model "
                        "(serve/spec_decode.py; greedy output stays "
                        "bit-identical to the unsped engine)")
    p.add_argument("--serve-draft-len", type=int, default=None,
                   dest="serve_draft_len",
                   help="max draft tokens verified per step (default 4)")
    p.add_argument("--serve-draft-model", default=None,
                   dest="serve_draft_model",
                   help="draft model name for --serve-spec-decode draft, "
                        "optionally \"name@ckpt_dir\" to restore its params")
    p.add_argument("--serve-slo", action="store_true", default=None,
                   dest="serve_slo",
                   help="record per-request span traces and sliding-window "
                        "TTFT/ITL quantiles (serve/slo.py); artifacts land "
                        "in the checkpoint dir (slo.jsonl, reqtrace.*.json)")
    p.add_argument("--serve-slo-window", type=int, default=None,
                   dest="serve_slo_window",
                   help="sliding-window size in samples per replica/role "
                        "(default 256)")
    p.add_argument("--serve-slo-ttft-ms", type=float, default=None,
                   dest="serve_slo_ttft_ms",
                   help="TTFT SLO target in ms (0 = track quantiles only)")
    p.add_argument("--serve-slo-itl-ms", type=float, default=None,
                   dest="serve_slo_itl_ms",
                   help="inter-token-latency SLO target in ms (0 = track "
                        "quantiles only)")
    p.add_argument("--serve-trace-events", type=int, default=None,
                   dest="serve_trace_events",
                   help="request-span ring-buffer capacity per replica; "
                        "overflow rotates generations and counts "
                        "dropped_spans (default 4096)")
    p.add_argument("--xcache", action="store_true", default=None,
                   help="persistent executable cache (core/xcache.py): "
                        "serialize the compiled train step under "
                        "<checkpoint-dir>/xcache keyed by a topology/knob "
                        "fingerprint so elastic relaunches at a seen "
                        "topology skip XLA compilation")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"],
                   help="force a jax platform (dev: run the TPU code path on CPU)")
    p.add_argument("--fake-devices", type=int, default=None,
                   help="with --platform cpu: number of fake host devices")
    return p


def config_from_args(args) -> "Config":
    from pytorch_distributed_training_example_tpu.utils.config import Config, from_preset

    cfg = from_preset(args.config) if args.config else Config()
    field_names = {f.name for f in dataclasses.fields(Config)}
    overrides = {k: v for k, v in vars(args).items()
                 if k in field_names and v is not None}
    cfg = cfg.replace(**overrides)
    if args.mesh:
        from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

        axes = mesh_lib.normalize_axes(
            dict(kv.split("=") for kv in args.mesh.split(",")))
        cfg = cfg.replace(**{f"mesh_{k}": int(v) for k, v in axes.items()})
    return cfg


def main(argv=None):
    args = build_parser().parse_args(argv)

    import os

    if args.fake_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.fake_devices}"
        ).strip()
    # Honor --platform, falling back to the JAX_PLATFORMS env var. The env
    # var alone is not enough here: site customizations that pre-import jax
    # (e.g. TPU plugin registration hooks) can pin the platform before this
    # process' env is consulted, so re-assert it through jax.config.
    platform = args.platform or os.environ.get("JAX_PLATFORMS_OVERRIDE")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    # Sharding-invariant RNG: jax 0.4.x defaults threefry_partitionable to
    # False, where a param initialized under a sharded mesh draws DIFFERENT
    # bits than the same seed on one device — checkpoints and loss curves
    # would then depend on topology. True is the jax 0.5+ default.
    import jax

    jax.config.update("jax_threefry_partitionable", True)

    # Persistent compile cache: repeat invocations (dev loops, restarts,
    # --resume) skip XLA recompilation. Opt out / relocate via env. Under
    # --xcache the cache co-locates with the serialized executables in
    # <checkpoint-dir>/xcache so it survives with the run, and it doubles
    # as the warm-restart fallback where executable serialization is
    # unsupported (core/xcache.py docstring).
    if os.environ.get("JAX_COMPILATION_CACHE_DIR", "unset") == "unset":
        import jax

        if args.xcache and args.checkpoint_dir:
            cache_dir = os.path.join(args.checkpoint_dir, "xcache", "jaxcache")
        else:
            cache_dir = "/tmp/pdtx_compile_cache"
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    # Bootstrap BEFORE touching jax.devices(): in multi-host mode every
    # process must rendezvous first (SURVEY.md §3.1 boundary).
    from pytorch_distributed_training_example_tpu.core import distributed

    if args.distributed:
        distributed.init_process_group(args.coordinator, args.num_processes,
                                       args.process_id)

    cfg = config_from_args(args)

    if cfg.serve:
        from pytorch_distributed_training_example_tpu.serve import run as serve_run

        serve_run.main(cfg)
        return 0

    from pytorch_distributed_training_example_tpu.core.trainer import Trainer

    trainer = Trainer(cfg)
    if args.evaluate:
        # Reference-CLI parity: the canonical ImageNet example's --evaluate
        # runs validation on the (resumed) model and exits. Scoring a fresh
        # init is never what the user meant — fail loudly.
        if not trainer.resumed:
            raise SystemExit(
                "--evaluate needs restored weights: pass --resume with a "
                "committed checkpoint (nothing was loaded)")
        trainer.evaluate(max(trainer.start_epoch - 1, 0))
        trainer.metric_logger.close()
        return 0
    trainer.train()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
