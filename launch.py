#!/usr/bin/env python
"""Multi-process launcher — the ``torchrun`` equivalent (SURVEY.md §2b N8).

On a real TPU pod each *host* runs one process and the TPU runtime provides
the cluster env, so ``launch.py`` mostly matters for local multi-process CPU
testing and for explicit on-host pods:

    python launch.py --nprocs 4 -- main.py --distributed --config gpt2_124m

spawns N processes with COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID set
(plus per-process CPU device partitioning when --cpu-devices is given),
streams rank-0 output, and propagates the first non-zero exit — torchrun's
contract, minus elasticity (TPU slices are gang-scheduled; recovery is
restart-from-checkpoint, SURVEY.md §5 failure detection).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--nprocs", type=int, default=2)
    p.add_argument("--coordinator-port", type=int, default=None)
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="fake CPU devices per process (testing without TPUs)")
    p.add_argument("--log-dir", default="/tmp",
                   help="directory for non-rank-0 stdout/stderr logs "
                        "(launch_rankN.log)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- script.py args...")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("no command given; usage: launch.py --nprocs N -- main.py ...")

    port = args.coordinator_port or free_port()
    os.makedirs(args.log_dir, exist_ok=True)
    procs = []
    for rank in range(args.nprocs):
        env = os.environ.copy()
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = str(args.nprocs)
        env["PROCESS_ID"] = str(rank)
        # torchrun-compatible aliases
        env["MASTER_ADDR"], env["MASTER_PORT"] = "127.0.0.1", str(port)
        env["WORLD_SIZE"], env["RANK"] = str(args.nprocs), str(rank)
        if args.cpu_devices:
            env["JAX_PLATFORMS"] = "cpu"
            # Belt and braces: JAX_PLATFORMS_OVERRIDE is re-asserted through
            # jax.config by main.py, surviving sitecustomize hooks that pin a
            # TPU platform during interpreter startup.
            env["JAX_PLATFORMS_OVERRIDE"] = "cpu"
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count={args.cpu_devices}").strip()
        if rank == 0:
            out = err = None
        else:
            out = err = open(
                os.path.join(args.log_dir, f"launch_rank{rank}.log"), "w")
        procs.append(subprocess.Popen([sys.executable, *cmd], env=env,
                                      stdout=out, stderr=err))

    def kill_all(*_):
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()

    signal.signal(signal.SIGINT, kill_all)
    signal.signal(signal.SIGTERM, kill_all)

    # Poll ALL ranks: the first failure tears the job down immediately
    # (a dead rank would otherwise leave the rest blocked in a collective
    # and the launcher hung in a serial wait()).
    import time

    code = None
    while code is None:
        time.sleep(0.2)
        rcs = [pr.poll() for pr in procs]
        failed = [rc for rc in rcs if rc not in (None, 0)]
        if failed:
            code = failed[0]
            kill_all()
        elif all(rc == 0 for rc in rcs):
            code = 0
    for pr in procs:
        try:
            pr.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pr.kill()
    return code


if __name__ == "__main__":
    raise SystemExit(main())
