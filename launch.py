#!/usr/bin/env python
"""Multi-process launcher — the ``torchrun`` equivalent (SURVEY.md §2b N8).

On a real TPU pod each *host* runs one process and the TPU runtime provides
the cluster env, so ``launch.py`` mostly matters for local multi-process CPU
testing and for explicit on-host pods:

    python launch.py --nprocs 4 -- main.py --distributed --config gpt2_124m

spawns N processes with COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID set
(plus per-process CPU device partitioning when --cpu-devices is given),
streams rank-0 output, and propagates the first non-zero exit — torchrun's
contract, minus elasticity (TPU slices are gang-scheduled; recovery is
restart-from-checkpoint, SURVEY.md §5 failure detection).

Supervisor mode (``--restart-policy``): when a run exits with the distinct
preemption code (resilience.PREEMPTED_EXIT_CODE — the trainer's
graceful-shutdown path after a SIGTERM took its emergency checkpoint), or
with any failure under ``on-failure``, the whole gang is relaunched with
``--resume auto`` appended, up to ``--max-restarts`` times with exponential
backoff. This is the "gang-scheduled slices get preempted and restart from
the latest checkpoint" recovery loop, run locally.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

try:
    # resilience.py deliberately imports no jax — safe in the launcher.
    from pytorch_distributed_training_example_tpu.utils.resilience import (
        PREEMPTED_EXIT_CODE)
except ImportError:  # stripped deployments: keep the launcher standalone
    PREEMPTED_EXIT_CODE = 75


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


_interrupted = False


def run_once(args, cmd) -> int:
    """Spawn the gang once, poll all ranks, return the first failure code."""
    # Fresh port per attempt: the previous attempt's coordinator socket can
    # linger in TIME_WAIT and wedge the rendezvous of a restart.
    port = args.coordinator_port or free_port()
    procs = []
    for rank in range(args.nprocs):
        env = os.environ.copy()
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = str(args.nprocs)
        env["PROCESS_ID"] = str(rank)
        # torchrun-compatible aliases
        env["MASTER_ADDR"], env["MASTER_PORT"] = "127.0.0.1", str(port)
        env["WORLD_SIZE"], env["RANK"] = str(args.nprocs), str(rank)
        if args.cpu_devices:
            env["JAX_PLATFORMS"] = "cpu"
            # Belt and braces: JAX_PLATFORMS_OVERRIDE is re-asserted through
            # jax.config by main.py, surviving sitecustomize hooks that pin a
            # TPU platform during interpreter startup.
            env["JAX_PLATFORMS_OVERRIDE"] = "cpu"
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count={args.cpu_devices}").strip()
        if rank == 0:
            out = err = None
        else:
            out = err = open(
                os.path.join(args.log_dir, f"launch_rank{rank}.log"), "w")
        procs.append(subprocess.Popen([sys.executable, *cmd], env=env,
                                      stdout=out, stderr=err))

    def kill_all(*signal_args):
        if signal_args:
            # Operator-initiated teardown (Ctrl-C / SIGTERM to the launcher):
            # the supervisor must NOT restart what the human just killed.
            global _interrupted
            _interrupted = True
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()

    signal.signal(signal.SIGINT, kill_all)
    signal.signal(signal.SIGTERM, kill_all)

    # Poll ALL ranks: the first failure tears the job down immediately
    # (a dead rank would otherwise leave the rest blocked in a collective
    # and the launcher hung in a serial wait()).
    code = None
    while code is None:
        time.sleep(0.2)
        rcs = [pr.poll() for pr in procs]
        failed = [rc for rc in rcs if rc not in (None, 0)]
        if failed:
            code = failed[0]
            kill_all()
        elif all(rc == 0 for rc in rcs):
            code = 0
    for pr in procs:
        try:
            pr.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pr.kill()
    return code


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--nprocs", type=int, default=2)
    p.add_argument("--coordinator-port", type=int, default=None)
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="fake CPU devices per process (testing without TPUs)")
    p.add_argument("--log-dir", default="/tmp",
                   help="directory for non-rank-0 stdout/stderr logs "
                        "(launch_rankN.log)")
    p.add_argument("--restart-policy", default="never",
                   choices=["never", "on-preempt", "on-failure"],
                   help="supervisor mode: relaunch the gang with --resume "
                        "auto after a preemption exit (code "
                        f"{PREEMPTED_EXIT_CODE}; on-preempt) or after any "
                        "non-zero exit (on-failure)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="restart budget for the supervisor (per launcher run)")
    p.add_argument("--restart-backoff", type=float, default=1.0,
                   help="base seconds between restarts; doubles per restart")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- script.py args...")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("no command given; usage: launch.py --nprocs N -- main.py ...")
    os.makedirs(args.log_dir, exist_ok=True)

    restarts = 0
    while True:
        code = run_once(args, cmd)
        if code == 0 or args.restart_policy == "never" or _interrupted:
            return code
        if args.restart_policy == "on-preempt" and code != PREEMPTED_EXIT_CODE:
            return code
        if restarts >= args.max_restarts:
            print(f"launch.py: restart budget exhausted "
                  f"({args.max_restarts}); last exit code {code}",
                  file=sys.stderr)
            return code
        restarts += 1
        delay = args.restart_backoff * 2 ** (restarts - 1)
        print(f"launch.py: exit code {code} -> restart {restarts}/"
              f"{args.max_restarts} with --resume auto in {delay:.1f}s",
              file=sys.stderr)
        time.sleep(delay)
        if _interrupted:  # Ctrl-C during the backoff window
            return code
        if "--resume" not in cmd:
            # argparse last-wins makes appending safe even if a later restart
            # re-appends; guard anyway to keep the command line readable.
            cmd = [*cmd, "--resume", "auto"]


if __name__ == "__main__":
    raise SystemExit(main())
