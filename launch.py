#!/usr/bin/env python
"""Multi-process launcher — the ``torchrun`` equivalent (SURVEY.md §2b N8).

On a real TPU pod each *host* runs one process and the TPU runtime provides
the cluster env, so ``launch.py`` mostly matters for local multi-process CPU
testing and for explicit on-host pods:

    python launch.py --nprocs 4 -- main.py --distributed --config gpt2_124m

spawns N processes with COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID set
(plus per-process CPU device partitioning when --cpu-devices is given),
streams rank-0 output, and propagates the first non-zero exit — torchrun's
contract, including elasticity (``--elastic``, below).

Supervisor mode (``--restart-policy``): when a run exits with the distinct
preemption code (resilience.PREEMPTED_EXIT_CODE — the trainer's
graceful-shutdown path after a SIGTERM took its emergency checkpoint), or
with any failure under ``on-failure``, the whole gang is relaunched with
``--resume auto`` appended, up to ``--max-restarts`` times with exponential
backoff. This is the "gang-scheduled slices get preempted and restart from
the latest checkpoint" recovery loop, run locally.

Elastic mode (``--elastic MIN[:MAX]``): before each restart the supervisor
reads the dead-host records (``dead_hosts.jsonl`` in the child's
``--checkpoint-dir``, written by an abruptly dying attempt — chaos
``kill_host`` or a real hard failure) and relaunches at the surviving world
size instead of the original one. The abrupt host-loss exit code
(resilience.HOST_LOST_EXIT_CODE) is restartable under any restart policy
when ``--elastic`` is set. Below MIN the supervisor gives up; the trainer
side (``main.py --elastic``) rebuilds the mesh at the new size and rescales
the batch geometry under ``--elastic-policy`` (utils/elastic.py).

The world also grows back: a host-return record (``returned_hosts.jsonl``,
written by whoever notices the repair — a node manager, a probe, the host
itself) cancels its dead record, and the next relaunch runs at
``base_world - |currently dead|``, capped by MAX and the launch-time size.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

try:
    # resilience.py / elastic.py deliberately import no jax — safe here.
    from pytorch_distributed_training_example_tpu.utils.resilience import (
        HOST_LOST_EXIT_CODE, PREEMPTED_EXIT_CODE, retriable_io)
    from pytorch_distributed_training_example_tpu.utils.elastic import (
        effective_dead_hosts)
except ImportError:  # stripped deployments: keep the launcher standalone
    PREEMPTED_EXIT_CODE = 75
    HOST_LOST_EXIT_CODE = 76

    def effective_dead_hosts(directory):
        return set()

    def retriable_io(fn, *args, _what="io", _attempts=4,
                     _base_delay_s=0.05, **kwargs):
        return fn(*args, **kwargs)


def _read_json(path):
    with open(path) as fh:
        return json.load(fh)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def probe_port(port: int) -> bool:
    """True when ``port`` is actually bindable right now."""
    try:
        with socket.socket() as s:
            s.bind(("", port))
        return True
    except OSError:
        return False


def coordinator_port(preferred: int | None) -> int:
    """Pick a bindable coordinator port, preferring the configured one.

    A supervisor restart previously burned a whole restart-budget attempt on
    EADDRINUSE when the preferred port (or the freshly allocated one, in a
    rare close-to-spawn race) was still held — e.g. the dying attempt's
    socket lingering outside TIME_WAIT, or another job grabbing it. Probe
    before spawning children and fall back to a fresh port with a warning
    instead.
    """
    candidates = ([preferred] if preferred else []) + \
        [free_port() for _ in range(3)]
    for i, port in enumerate(candidates):
        if probe_port(port):
            if i > 0 and preferred:
                print(f"launch.py: coordinator port {preferred} is not "
                      f"bindable — using {port} instead", file=sys.stderr)
            return port
    raise OSError(
        f"no bindable coordinator port found (tried {candidates})")


_interrupted = False


def run_once(args, cmd) -> int:
    """Spawn the gang once, poll all ranks, return the first failure code."""
    # Fresh port per attempt: the previous attempt's coordinator socket can
    # linger in TIME_WAIT and wedge the rendezvous of a restart. Probed for
    # bindability so a held port costs a warning, not a restart attempt.
    port = coordinator_port(args.coordinator_port)
    procs = []
    for rank in range(args.nprocs):
        env = os.environ.copy()
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = str(args.nprocs)
        env["PROCESS_ID"] = str(rank)
        # torchrun-compatible aliases
        env["MASTER_ADDR"], env["MASTER_PORT"] = "127.0.0.1", str(port)
        env["WORLD_SIZE"], env["RANK"] = str(args.nprocs), str(rank)
        if args.cpu_devices:
            env["JAX_PLATFORMS"] = "cpu"
            # Belt and braces: JAX_PLATFORMS_OVERRIDE is re-asserted through
            # jax.config by main.py, surviving sitecustomize hooks that pin a
            # TPU platform during interpreter startup.
            env["JAX_PLATFORMS_OVERRIDE"] = "cpu"
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                f" --xla_force_host_platform_device_count={args.cpu_devices}").strip()
        if rank == 0:
            out = err = None
        else:
            out = err = retriable_io(
                open, os.path.join(args.log_dir, f"launch_rank{rank}.log"),
                "w", _what="rank log open")
        procs.append(subprocess.Popen([sys.executable, *cmd], env=env,
                                      stdout=out, stderr=err))

    def kill_all(*signal_args):
        if signal_args:
            # Operator-initiated teardown (Ctrl-C / SIGTERM to the launcher):
            # the supervisor must NOT restart what the human just killed.
            global _interrupted
            _interrupted = True
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()

    signal.signal(signal.SIGINT, kill_all)
    signal.signal(signal.SIGTERM, kill_all)

    # Poll ALL ranks: the first failure tears the job down immediately
    # (a dead rank would otherwise leave the rest blocked in a collective
    # and the launcher hung in a serial wait()).
    code = None
    while code is None:
        time.sleep(0.2)
        rcs = [pr.poll() for pr in procs]
        failed = [rc for rc in rcs if rc not in (None, 0)]
        if failed:
            code = failed[0]
            kill_all()
        elif all(rc == 0 for rc in rcs):
            code = 0
    for pr in procs:
        try:
            pr.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pr.kill()
    return code


def parse_elastic(spec: str) -> tuple[int, int]:
    """``MIN`` or ``MIN:MAX`` -> (min_world, max_world)."""
    lo, _, hi = spec.partition(":")
    min_world = int(lo)
    max_world = int(hi) if hi else 1 << 30
    if min_world < 1 or max_world < min_world:
        raise ValueError(f"--elastic expects MIN[:MAX] with 1 <= MIN <= MAX, "
                         f"got {spec!r}")
    return min_world, max_world


def find_flag(cmd: list[str], flag: str) -> str | None:
    """Value of ``flag <value>`` in the child command line (last wins)."""
    value = None
    for i, tok in enumerate(cmd[:-1]):
        if tok == flag:
            value = cmd[i + 1]
    return value


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--nprocs", type=int, default=2)
    p.add_argument("--coordinator-port", type=int, default=None)
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="fake CPU devices per process (testing without TPUs)")
    p.add_argument("--log-dir", default="/tmp",
                   help="directory for non-rank-0 stdout/stderr logs "
                        "(launch_rankN.log)")
    p.add_argument("--restart-policy", default="never",
                   choices=["never", "on-preempt", "on-failure"],
                   help="supervisor mode: relaunch the gang with --resume "
                        "auto after a preemption exit (code "
                        f"{PREEMPTED_EXIT_CODE}; on-preempt) or after any "
                        "non-zero exit (on-failure)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="restart budget for the supervisor (per launcher run)")
    p.add_argument("--restart-backoff", type=float, default=1.0,
                   help="base seconds between restarts; doubles per restart")
    p.add_argument("--elastic", default=None, metavar="MIN[:MAX]",
                   help="elastic supervisor: on restart, shrink the world to "
                        "the surviving host set (dead_hosts.jsonl in the "
                        "child's --checkpoint-dir) instead of relaunching "
                        "the full gang; give up below MIN hosts. Makes the "
                        f"abrupt host-loss exit ({HOST_LOST_EXIT_CODE}) "
                        "restartable under any restart policy")
    p.add_argument("--trace-merge", default="auto", choices=["auto", "off"],
                   help="after the gang exits (any code), merge per-rank "
                        "telemetry in the child's --checkpoint-dir into one "
                        "fleet trace/goodput/straggler report "
                        "(benchmarks/trace_merge.py); auto = when artifacts "
                        "exist")
    p.add_argument("--fleet", default=None, metavar="JOBS_JSON",
                   help="multi-job control plane: run the utils/scheduler.py "
                        "loop over the jobs in JOBS_JSON sharing one device "
                        "pool — priorities, SIGTERM preemption (exit "
                        f"{PREEMPTED_EXIT_CODE} requeues without burning the "
                        "restart budget), doubling backoff, and backfill of "
                        "devices freed by dead hosts; ignores the "
                        "single-gang flags")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="with --fleet: serve cluster + per-job pdtx_fleet_* "
                        "gauges on one /metrics endpoint (0 = ephemeral)")
    p.add_argument("--fleet-poll", type=float, default=0.05,
                   help="with --fleet: scheduler loop poll interval seconds")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- script.py args...")
    args = p.parse_args(argv)
    if args.fleet is not None:
        retriable_io(os.makedirs, args.log_dir, exist_ok=True,
                     _what="log dir create")
        return run_fleet(args)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("no command given; usage: launch.py --nprocs N -- main.py ...")
    elastic = None
    if args.elastic is not None:
        if args.restart_policy == "never":
            p.error("--elastic needs a restart policy (on-preempt or "
                    "on-failure): shrinking happens at relaunch")
        try:
            elastic = parse_elastic(args.elastic)
        except ValueError as e:
            p.error(str(e))
    retriable_io(os.makedirs, args.log_dir, exist_ok=True,
                 _what="log dir create")
    code = supervise(args, cmd, elastic)
    if args.trace_merge == "auto":
        # Post-mortem-friendly: the merge runs after EVERY terminal outcome
        # — success, budget exhaustion, elastic give-up — because the fleet
        # view matters most when the run died. Best-effort by design.
        merge_traces(cmd)
    return code


def merge_traces(cmd: list[str]) -> None:
    """Merge the attempt's telemetry artifacts into the fleet view (one
    subprocess call of ``benchmarks/trace_merge.py``; skipped quietly when
    there is nothing to merge or the script is absent)."""
    ckdir = find_flag(cmd, "--checkpoint-dir")
    if not ckdir or not os.path.isdir(ckdir):
        return
    try:
        names = retriable_io(os.listdir, ckdir, _what="trace merge scan")
    except OSError:
        return
    if not any(n.startswith("trace_events") and n.endswith(".json")
               for n in names):
        return
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "benchmarks", "trace_merge.py")
    if not os.path.exists(script):
        return
    try:
        res = subprocess.run([sys.executable, script, ckdir],
                             capture_output=True, text=True, timeout=120)
        out = (res.stdout or res.stderr or "").strip()
        tag = "" if res.returncode == 0 else f" (exit {res.returncode})"
        print(f"launch.py: trace merge{tag}:\n{out}", file=sys.stderr)
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"launch.py: trace merge failed ({e})", file=sys.stderr)


def start_reshard(ckdir: str, world: int):
    """Kick off the background checkpoint re-shard (core/reshard.py).

    The supervisor knows the surviving world the moment it reads the dead
    host records — *before* the restart backoff ends — so the consolidation
    of the newest committed checkpoint overlaps the backoff window instead
    of the relaunch's restore path. Best-effort: a failure to even spawn
    just means the relaunch restores the original layout.
    """
    mod = "pytorch_distributed_training_example_tpu.core.reshard"
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", mod, "--checkpoint-dir", ckdir,
             "--world", str(world)],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)
    except OSError as e:
        print(f"launch.py: background re-shard failed to start ({e})",
              file=sys.stderr)
        return None
    print(f"launch.py: background re-shard started for world {world} "
          f"(pid {proc.pid})", file=sys.stderr)
    return proc


def finish_reshard(proc, ckdir: str, timeout_s: float = 60.0) -> None:
    """Join the background re-shard before relaunching.

    A hung or failed re-shard must never block the restart — the relaunch
    simply restores the original (un-consolidated) layout. Killing it is
    safe at any instant: reshard.py commits via the same ``.old`` set-aside
    swap as checkpoint.py, so a committed copy of the step always exists;
    only the ``.saving.reshard`` attempt dir can be left behind, and we
    sweep those here (the Checkpointer never prunes that suffix).
    """
    try:
        _, err = proc.communicate(timeout=timeout_s)
        code = proc.returncode
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        code = None
    if code == 0:
        print("launch.py: background re-shard ready — the relaunch restores "
              "a consolidated checkpoint", file=sys.stderr)
        return
    if code is None:
        print(f"launch.py: background re-shard overran the {timeout_s:.0f}s "
              "restart window — killed; the relaunch restores the original "
              "layout", file=sys.stderr)
    else:
        tail = (err or "").strip().splitlines()
        detail = f": {tail[-1]}" if tail else ""
        print(f"launch.py: background re-shard exit {code}{detail}",
              file=sys.stderr)
    try:
        for name in retriable_io(os.listdir, ckdir, _what="reshard sweep"):
            if name.startswith("step_") and name.endswith(".saving.reshard"):
                shutil.rmtree(os.path.join(ckdir, name), ignore_errors=True)
    except OSError:
        pass


def clear_stale_run_id(ckdir: str | None) -> None:
    """Remove a torn ``run_id.json`` before relaunching.

    An attempt killed mid-write (host loss, preemption during startup) can
    leave the shared run-identity file truncated. Rank 0 of the relaunch
    refuses to trust it and every rank would fall back to per-process ids —
    telemetry artifacts from the same logical run would then never merge.
    The supervisor owns the restart boundary, so it clears the wreck here,
    loudly; a *healthy* file is preserved (attempt counters must keep
    monotonically increasing across restarts).
    """
    if not ckdir:
        return
    path = os.path.join(ckdir, "run_id.json")
    if not os.path.exists(path):
        return
    try:
        str(retriable_io(_read_json, path, _what="run_id check")["run_id"])
        return  # healthy: keep the shared identity
    except (OSError, ValueError, KeyError, TypeError):
        pass
    print(f"launch.py: {path} is torn (an earlier attempt died mid-write) — "
          "clearing it so the relaunch re-establishes a shared run identity",
          file=sys.stderr)
    try:
        retriable_io(os.unlink, path, _what="run_id clear")
    except OSError as e:
        print(f"launch.py: could not clear torn run_id.json ({e})",
              file=sys.stderr)


def supervise(args, cmd, elastic) -> int:
    """The restart loop: run the gang until a terminal exit code."""
    # The elastic "world" is whichever knob actually multiplexes hosts in
    # this launch: real processes when --nprocs > 1, else fake CPU devices
    # (the single-process local pod used by tests and dryrun drills).
    world_attr = "nprocs" if args.nprocs > 1 else "cpu_devices"
    dead_seen: set[int] = set()
    base_world: int | None = None  # launch-time size: the grow ceiling
    reshard_proc = None  # background checkpoint consolidation, one at a time

    restarts = 0
    while True:
        code = run_once(args, cmd)
        if code == 0 or _interrupted:
            return code
        restartable = (args.restart_policy == "on-failure"
                       or (args.restart_policy == "on-preempt"
                           and code == PREEMPTED_EXIT_CODE)
                       or (elastic is not None
                           and code == HOST_LOST_EXIT_CODE))
        if args.restart_policy == "never" or not restartable:
            return code
        if restarts >= args.max_restarts:
            print(f"launch.py: restart budget exhausted "
                  f"({args.max_restarts}); last exit code {code}",
                  file=sys.stderr)
            return code
        if elastic is not None:
            ckdir = find_flag(cmd, "--checkpoint-dir")
            # Absolute accounting, not incremental: the next world size is
            # always base_world minus the hosts dead RIGHT NOW (dead minus
            # returned, count-based), so a host-return record GROWS the
            # world back — capped by the launch-time size and --elastic MAX.
            dead_now = effective_dead_hosts(ckdir) if ckdir else set()
            new_dead = dead_now - dead_seen
            returned = dead_seen - dead_now
            if new_dead or returned:
                dead_seen = dead_now
                world = getattr(args, world_attr) or 1
                if base_world is None:
                    # First size change: ``world`` is still the launch size.
                    base_world = world
                min_world, max_world = elastic
                new_world = min(max(base_world - len(dead_now), 0), max_world)
                if new_world < min_world:
                    print(f"launch.py: elastic give-up — {len(new_dead)} "
                          f"host(s) {sorted(new_dead)} lost, surviving world "
                          f"{new_world} is below --elastic min {min_world}",
                          file=sys.stderr)
                    return code
                if new_dead:
                    print(f"launch.py: elastic — host(s) {sorted(new_dead)} "
                          f"lost, relaunching at world size {new_world} "
                          f"(was {world})", file=sys.stderr)
                if returned:
                    print(f"launch.py: elastic — host(s) {sorted(returned)} "
                          f"returned, relaunching at world size {new_world} "
                          f"(was {world})", file=sys.stderr)
                setattr(args, world_attr, new_world)
                if ckdir and new_world and reshard_proc is None:
                    # Overlap the backoff: consolidate the newest committed
                    # checkpoint for the surviving world while nothing runs.
                    reshard_proc = start_reshard(ckdir, new_world)
        restarts += 1
        delay = args.restart_backoff * 2 ** (restarts - 1)
        print(f"launch.py: exit code {code} -> restart {restarts}/"
              f"{args.max_restarts} with --resume auto in {delay:.1f}s",
              file=sys.stderr)
        time.sleep(delay)
        if reshard_proc is not None:
            finish_reshard(reshard_proc,
                           find_flag(cmd, "--checkpoint-dir") or "")
            reshard_proc = None
        clear_stale_run_id(find_flag(cmd, "--checkpoint-dir"))
        if _interrupted:  # Ctrl-C during the backoff window
            return code
        if "--resume" not in cmd:
            # argparse last-wins makes appending safe even if a later restart
            # re-appends; guard anyway to keep the command line readable.
            cmd = [*cmd, "--resume", "auto"]


def write_cluster_goodput(sched, log_dir: str) -> dict | None:
    """Fold each job's merged ``goodput.json`` into one cluster summary
    (``cluster_goodput.json`` in the fleet log dir) — distinct run_ids by
    construction, which is what ``check_regression.py --goodput --cluster``
    gates. Best-effort: jobs without telemetry just don't contribute."""
    from pytorch_distributed_training_example_tpu.utils import fleetobs
    from pytorch_distributed_training_example_tpu.utils import (
        scheduler as scheduler_lib)

    per_job = {}
    for name in sorted(sched.jobs):
        ckdir = sched.state(name).spec.checkpoint_dir
        if not ckdir:
            continue
        path = os.path.join(ckdir, "goodput.json")
        if not os.path.exists(path):
            continue
        try:
            per_job[name] = retriable_io(_read_json, path,
                                         _what="fleet goodput read")
        except (OSError, ValueError):
            print(f"launch.py: fleet — unreadable goodput for {name} "
                  f"({path})", file=sys.stderr)
    if not per_job:
        return None
    cluster = fleetobs.aggregate_cluster_goodput(per_job)
    fleetobs.write_json_atomic(
        os.path.join(log_dir, scheduler_lib.CLUSTER_GOODPUT_FILE), cluster)
    return cluster


def run_fleet(args) -> int:
    """The multi-job control plane: spawn/preempt/relaunch what the
    scheduler decides, over one shared pool of fake CPU devices.

    Each job runs as one local process whose ``world`` is its fake-device
    count (the same local-pod shape ``--nprocs 1 --cpu-devices N`` uses and
    the dryrun drills test); on a real pod the worlds would map to hosts.
    Preemption is a SIGTERM — the trainer's resilience path takes its
    emergency checkpoint and exits PREEMPTED_EXIT_CODE, and the scheduler
    requeues it; relaunches append ``--resume auto``.
    """
    from pytorch_distributed_training_example_tpu.utils import fleetobs
    from pytorch_distributed_training_example_tpu.utils import (
        scheduler as scheduler_lib)

    pool, specs = scheduler_lib.load_jobs(args.fleet)
    sched = scheduler_lib.FleetScheduler(pool, specs, log_dir=args.log_dir)
    print(f"launch.py: fleet — {len(specs)} job(s) over a pool of "
          f"{pool} device(s)", file=sys.stderr)
    procs: dict[str, subprocess.Popen] = {}
    logs: dict[str, object] = {}
    metrics = None
    if args.metrics_port is not None:
        metrics = fleetobs.MetricsServer(port=args.metrics_port).start()
        print(f"launch.py: fleet metrics on :{metrics.port}", file=sys.stderr)

    def stop_fleet(*_sig):
        global _interrupted
        _interrupted = True
        for pr in procs.values():
            if pr.poll() is None:
                pr.terminate()

    signal.signal(signal.SIGINT, stop_fleet)
    signal.signal(signal.SIGTERM, stop_fleet)

    def spawn(name: str, world: int) -> None:
        st = sched.state(name)
        cmd = list(st.spec.cmd)
        if st.attempts > 1 and "--resume" not in cmd:
            # argparse last-wins; same relaunch contract as supervise().
            cmd = [*cmd, "--resume", "auto"]
        port = coordinator_port(None)
        env = os.environ.copy()
        env.update(dict(st.spec.env))
        env["PDTX_JOB_KIND"] = st.spec.kind
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"], env["PROCESS_ID"] = "1", "0"
        env["MASTER_ADDR"], env["MASTER_PORT"] = "127.0.0.1", str(port)
        env["WORLD_SIZE"], env["RANK"] = "1", "0"
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_PLATFORMS_OVERRIDE"] = "cpu"
        env["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={world}").strip()
        if name not in logs:
            logs[name] = retriable_io(
                open, os.path.join(args.log_dir, f"fleet_{name}.log"), "a",
                _what="fleet log open")
        print(f"launch.py: fleet — launch {name} at world {world} "
              f"(attempt {st.attempts})", file=sys.stderr)
        procs[name] = subprocess.Popen([sys.executable, *cmd], env=env,
                                       stdout=logs[name], stderr=logs[name])

    last_obs_push = float("-inf")
    while not _interrupted:
        for name, pr in list(procs.items()):
            rc = pr.poll()
            if rc is not None:
                procs.pop(name)
                row = sched.on_exit(name, rc, time.monotonic())
                print(f"launch.py: fleet — {name} exited {rc}: "
                      f"{row['reason']}", file=sys.stderr)
        now = time.monotonic()
        decisions = sched.plan(now)
        for d in decisions:
            if d["action"] == "launch":
                spawn(d["job"], d["world"])
            elif d["action"] == "preempt":
                print(f"launch.py: fleet — preempt {d['job']}: "
                      f"{d['reason']}", file=sys.stderr)
                pr = procs.get(d["job"])
                if pr is not None and pr.poll() is None:
                    pr.send_signal(signal.SIGTERM)
        if metrics is not None:
            metrics.update(**sched.gauges())
            if now - last_obs_push >= 2.0:
                # Per-job artifact gauges at a gentle cadence: straggler
                # flag counts (r12 detection, previously write-only) so a
                # slow host is scrapeable while the fleet runs.
                last_obs_push = now
                for name in sched.jobs:
                    ckdir = sched.state(name).spec.checkpoint_dir
                    if not ckdir:
                        continue
                    rows = fleetobs.read_jsonl_tolerant(
                        os.path.join(ckdir, fleetobs.STRAGGLER_FILE))
                    if rows:
                        metrics.update(**fleetobs.straggler_gauges(
                            rows, prefix=f"fleet_straggler_{name}"))
        if sched.finished():
            break
        deadline = sched.next_deadline_s()
        if (not procs and not decisions
                and (deadline is None or deadline <= now)):
            # Whole pool free, every backoff expired, still nothing
            # placeable — the leftovers are permanently stuck (dependency
            # died checkpoint-less, or dead hosts pinned a range shut).
            for row in sched.mark_starved():
                print(f"launch.py: fleet — give up on {row['job']}: "
                      f"{row['reason']}", file=sys.stderr)
            break
        time.sleep(args.fleet_poll)

    for pr in procs.values():
        if pr.poll() is None:
            pr.terminate()
    for pr in procs.values():
        try:
            pr.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pr.kill()
    for fh in logs.values():
        fh.close()
    cluster = write_cluster_goodput(sched, args.log_dir)
    if cluster:
        print(f"launch.py: fleet — cluster goodput "
              f"{cluster.get('goodput_fraction')} coverage "
              f"{cluster.get('coverage')} over {len(cluster.get('jobs', []))}"
              f" job(s), {cluster.get('attempts')} attempt(s)",
              file=sys.stderr)
        if metrics is not None:
            metrics.update(
                fleet_goodput_fraction=cluster.get("goodput_fraction") or 0.0,
                fleet_goodput_coverage=cluster.get("coverage") or 0.0)
    states = {name: sched.state(name).status for name in sorted(sched.jobs)}
    print(f"launch.py: fleet — final states {states}", file=sys.stderr)
    if metrics is not None:
        metrics.update(**sched.gauges())
        metrics.stop()
    if _interrupted:
        return 130
    return 0 if all(s == scheduler_lib.DONE for s in states.values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
